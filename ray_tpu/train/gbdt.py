"""Distributed gradient-boosted decision trees (XGBoostTrainer).

Capability mirror of the reference's GBDT trainer family
(`python/ray/train/gbdt_trainer.py`, `train/xgboost/xgboost_trainer.py` —
data-parallel tree boosting over worker actors with Dataset ingest and
checkpointing).  xgboost/lightgbm are not in this image, so the algorithm
itself is implemented here, natively distributed the same way xgboost's
`tree_method=hist` + rabit AllReduce is: each worker actor holds one data
shard pre-binned to uint8, computes per-node (grad, hess) histograms for
its rows, and the driver sums histograms across workers — the sums are
EXACT, so N-worker training produces bit-identical trees to 1-worker
training — then broadcasts the chosen splits.  Communication per tree
level is `nodes x features x bins x 2` floats, independent of row count.

Supported objectives: ``reg:squarederror``, ``binary:logistic``,
``multi:softprob`` / ``multi:softmax`` (K trees per round, one per
class, softmax grad/hess — xgboost's multiclass scheme), and
``rank:pairwise`` (LambdaRank-style pairwise gradients within query
groups; shard boundaries snap to group boundaries so a group never
splits across workers).  All second-order boosting, xgboost-style gain
with L2 ``lambda`` and ``min_child_weight``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..air.checkpoint import Checkpoint
from ..air.result import Result

MAX_BINS = 64


def _softmax_rows(margin: np.ndarray) -> np.ndarray:
    """Row-wise softmax over [n, K] margins — ONE definition shared by
    shard gradients, model predict, and validation metrics."""
    z = margin - margin.max(axis=1, keepdims=True)
    p = np.exp(z)
    return p / p.sum(axis=1, keepdims=True)


def _pairwise_error(margin: np.ndarray, rel: np.ndarray,
                    groups: np.ndarray) -> tuple:
    """(mis-ordered pairs, ordered pairs) within query groups — shared
    by the shard train metric and validation scoring."""
    bad = total = 0
    for gid in np.unique(groups):
        rows = np.nonzero(groups == gid)[0]
        m, r = margin[rows], rel[rows]
        better = r[:, None] > r[None, :]
        bad += int((better & (m[:, None] <= m[None, :])).sum())
        total += int(better.sum())
    return bad, total


def _bin_matrix(X: np.ndarray, bin_edges: List[np.ndarray]) -> np.ndarray:
    """Quantize rows to uint8 bin ids — the ONE binning definition shared
    by training shards and the fitted model (exactness depends on it)."""
    Xb = np.empty(X.shape, dtype=np.uint8)
    for j, edges in enumerate(bin_edges):
        Xb[:, j] = np.searchsorted(edges, X[:, j], side="left")
    return Xb


# -- model -------------------------------------------------------------------


class _Tree:
    """Flat-array binary tree over binned features."""

    __slots__ = ("feature", "threshold_bin", "left", "right", "value")

    def __init__(self):
        self.feature: List[int] = []
        self.threshold_bin: List[int] = []
        self.left: List[int] = []
        self.right: List[int] = []
        self.value: List[float] = []

    def add_node(self) -> int:
        self.feature.append(-1)
        self.threshold_bin.append(0)
        self.left.append(-1)
        self.right.append(-1)
        self.value.append(0.0)
        return len(self.feature) - 1

    def predict_bins(self, Xb: np.ndarray) -> np.ndarray:
        """Vectorized traversal over pre-binned rows [n, features]."""
        out = np.zeros(len(Xb), dtype=np.float64)
        idx = np.arange(len(Xb))
        stack = [(0, idx)]
        while stack:
            node, rows = stack.pop()
            if self.feature[node] < 0:
                out[rows] = self.value[node]
                continue
            go_left = Xb[rows, self.feature[node]] <= \
                self.threshold_bin[node]
            stack.append((self.left[node], rows[go_left]))
            stack.append((self.right[node], rows[~go_left]))
        return out

    def to_dict(self) -> Dict[str, list]:
        return {k: list(getattr(self, k)) for k in self.__slots__}

    @classmethod
    def from_dict(cls, d: Dict[str, list]) -> "_Tree":
        t = cls()
        for k in cls.__slots__:
            setattr(t, k, list(d[k]))
        return t


class GBDTModel:
    """Fitted booster: bin edges + tree ensemble + base score."""

    def __init__(self, bin_edges: List[np.ndarray], objective: str,
                 base_score: float, learning_rate: float,
                 n_classes: int = 0):
        self.bin_edges = bin_edges
        self.objective = objective
        self.base_score = base_score
        self.learning_rate = learning_rate
        self.n_classes = n_classes          # 0 for scalar objectives
        self.trees: List[_Tree] = []
        self.tree_class: List[int] = []     # class each tree boosts

    def _bin(self, X: np.ndarray) -> np.ndarray:
        return _bin_matrix(X, self.bin_edges)

    def predict_margin(self, X: np.ndarray) -> np.ndarray:
        """[n] for scalar objectives, [n, K] for multiclass."""
        X = np.asarray(X, dtype=np.float64)
        Xb = self._bin(X)
        if self.n_classes:
            margin = np.full((len(X), self.n_classes), self.base_score)
            for tree, k in zip(self.trees, self.tree_class):
                margin[:, k] += self.learning_rate \
                    * tree.predict_bins(Xb)
            return margin
        margin = np.full(len(X), self.base_score)
        for tree in self.trees:
            margin += self.learning_rate * tree.predict_bins(Xb)
        return margin

    def predict(self, X: np.ndarray) -> np.ndarray:
        """Probabilities for binary:logistic, class probabilities for
        multi:softprob, class ids for multi:softmax, scores/values
        otherwise."""
        margin = self.predict_margin(X)
        if self.objective == "binary:logistic":
            return 1.0 / (1.0 + np.exp(-margin))
        if self.objective in ("multi:softprob", "multi:softmax"):
            p = _softmax_rows(margin)
            return np.argmax(p, axis=1) \
                if self.objective == "multi:softmax" else p
        return margin

    def to_dict(self) -> Dict[str, Any]:
        return {"bin_edges": [e.tolist() for e in self.bin_edges],
                "objective": self.objective,
                "base_score": self.base_score,
                "learning_rate": self.learning_rate,
                "n_classes": self.n_classes,
                "tree_class": list(self.tree_class),
                "trees": [t.to_dict() for t in self.trees]}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "GBDTModel":
        m = cls([np.asarray(e) for e in d["bin_edges"]], d["objective"],
                d["base_score"], d["learning_rate"],
                d.get("n_classes", 0))
        m.trees = [_Tree.from_dict(t) for t in d["trees"]]
        m.tree_class = list(d.get("tree_class",
                                  [0] * len(m.trees)))
        return m


# -- worker actor ------------------------------------------------------------


class _GBDTShard:
    """One data shard: binned features + running margins (actor body)."""

    def __init__(self, X: np.ndarray, y: np.ndarray,
                 bin_edges: List[np.ndarray], objective: str,
                 base_score: float, n_classes: int = 0,
                 groups: Optional[np.ndarray] = None):
        self.y = np.asarray(y, dtype=np.float64)
        X = np.asarray(X, dtype=np.float64)
        self.Xb = _bin_matrix(X, bin_edges)
        self.n_features = X.shape[1]
        self.objective = objective
        self.n_classes = n_classes
        self.groups = None if groups is None else \
            np.asarray(groups)
        if n_classes:
            self.margin = np.full((len(self.y), n_classes), base_score)
        else:
            self.margin = np.full(len(self.y), base_score)
        # node assignment of each row for the tree under construction
        self.node_of_row = np.zeros(len(self.y), dtype=np.int32)
        self.grad = np.zeros(len(self.y))
        self.hess = np.ones(len(self.y))

    def num_rows(self) -> int:
        return len(self.y)

    def _softmax(self) -> np.ndarray:
        return _softmax_rows(self.margin)

    def start_tree(self, class_k: int = 0) -> None:
        if self.objective == "binary:logistic":
            p = 1.0 / (1.0 + np.exp(-self.margin))
            self.grad = p - self.y
            self.hess = p * (1.0 - p)
        elif self.objective in ("multi:softprob", "multi:softmax"):
            # xgboost's multiclass scheme: one tree per class per
            # round, softmax grad/hess for THIS class's margin column
            pk = self._softmax()[:, class_k]
            self.grad = pk - (self.y == class_k)
            self.hess = np.maximum(pk * (1.0 - pk), 1e-16)
        elif self.objective == "rank:pairwise":
            self._rank_gradients()
        else:  # reg:squarederror
            self.grad = self.margin - self.y
            self.hess = np.ones(len(self.y))
        self.node_of_row[:] = 0

    def _rank_gradients(self) -> None:
        """LambdaRank-style pairwise grad/hess within query groups
        (xgboost rank:pairwise): for each pair i≻j in a group,
        rho = sigmoid(-(m_i - m_j)) pushes m_i up and m_j down."""
        self.grad = np.zeros(len(self.y))
        self.hess = np.zeros(len(self.y))
        for gid in np.unique(self.groups):
            rows = np.nonzero(self.groups == gid)[0]
            m, rel = self.margin[rows], self.y[rows]
            better = rel[:, None] > rel[None, :]         # i beats j
            rho = 1.0 / (1.0 + np.exp(m[:, None] - m[None, :]))
            rho = np.where(better, rho, 0.0)
            hs = np.where(better, rho * (1.0 - rho), 0.0)
            self.grad[rows] = -rho.sum(axis=1) + rho.sum(axis=0)
            self.hess[rows] = hs.sum(axis=1) + hs.sum(axis=0)
        self.hess = np.maximum(self.hess, 1e-16)

    def histograms(self, nodes: List[int]):
        """Per requested node: [features, bins] grad and hess sums."""
        out = {}
        for node in nodes:
            rows = np.nonzero(self.node_of_row == node)[0]
            g = np.zeros((self.n_features, MAX_BINS))
            h = np.zeros((self.n_features, MAX_BINS))
            if len(rows):
                gr, hr = self.grad[rows], self.hess[rows]
                for j in range(self.n_features):
                    bins = self.Xb[rows, j]
                    g[j] = np.bincount(bins, weights=gr,
                                       minlength=MAX_BINS)[:MAX_BINS]
                    h[j] = np.bincount(bins, weights=hr,
                                       minlength=MAX_BINS)[:MAX_BINS]
            out[node] = (g, h)
        return out

    def apply_splits(self, splits: Dict[int, tuple]) -> None:
        """splits: node -> (feature, threshold_bin, left_id, right_id)."""
        for node, (feat, thr, left, right) in splits.items():
            rows = np.nonzero(self.node_of_row == node)[0]
            go_left = self.Xb[rows, feat] <= thr
            self.node_of_row[rows[go_left]] = left
            self.node_of_row[rows[~go_left]] = right

    def finish_tree(self, leaf_values: Dict[int, float],
                    learning_rate: float, class_k: int = 0) -> None:
        values = np.zeros(int(self.node_of_row.max()) + 1 if len(self.y)
                          else 1)
        for node, v in leaf_values.items():
            if node < len(values):
                values[node] = v
        delta = learning_rate * values[self.node_of_row]
        if self.n_classes:
            self.margin[:, class_k] += delta
        else:
            self.margin += delta

    def eval_metric(self):
        """(sum_metric, count) for the trainer's running train metric."""
        if self.objective == "binary:logistic":
            p = np.clip(1.0 / (1.0 + np.exp(-self.margin)), 1e-12,
                        1 - 1e-12)
            loss = -(self.y * np.log(p) + (1 - self.y) * np.log(1 - p))
            return float(loss.sum()), len(self.y)
        if self.objective in ("multi:softprob", "multi:softmax"):
            p = np.clip(self._softmax(), 1e-12, 1.0)
            rows = np.arange(len(self.y))
            loss = -np.log(p[rows, self.y.astype(int)])
            return float(loss.sum()), len(self.y)
        if self.objective == "rank:pairwise":
            bad, total = _pairwise_error(self.margin, self.y,
                                         self.groups)
            return float(bad), max(total, 1)
        return float(((self.margin - self.y) ** 2).sum()), len(self.y)


# -- trainer -----------------------------------------------------------------


def _to_xy(dataset: Any, label: str, group: Optional[str] = None):
    import pandas as pd
    df = dataset.to_pandas() if hasattr(dataset, "to_pandas") else dataset
    assert isinstance(df, pd.DataFrame)
    y = df[label].to_numpy(dtype=np.float64)
    drop = [label] + ([group] if group else [])
    X = df.drop(columns=drop).to_numpy(dtype=np.float64)
    groups = None if group is None else df[group].to_numpy()
    return X, y, groups


class XGBoostTrainer:
    """Data-parallel histogram GBDT over worker actors.

    API-shaped like the reference's XGBoostTrainer: xgboost-style
    ``params`` (objective, eta/learning_rate, max_depth, lambda,
    min_child_weight, gamma), ``num_boost_round``, Dataset ingest via
    ``datasets={"train": ..., "valid": ...}``, and a Checkpoint carrying
    the fitted model.
    """

    def __init__(self, *, params: Dict[str, Any], num_boost_round: int,
                 datasets: Dict[str, Any], label_column: str,
                 num_workers: int = 2, group_column: Optional[str] = None,
                 scaling_config: Optional[Any] = None):
        if "train" not in datasets:
            raise ValueError("datasets must contain a 'train' split")
        self.params = dict(params)
        self.num_boost_round = num_boost_round
        self.datasets = datasets
        self.label_column = label_column
        # rank:pairwise query groups (xgboost's DMatrix.set_group,
        # expressed as a per-row column like the label)
        self.group_column = group_column
        if scaling_config is not None and \
                getattr(scaling_config, "num_workers", None):
            num_workers = scaling_config.num_workers
        self.num_workers = max(1, num_workers)

    # xgboost param names with their defaults
    def _p(self, *names, default):
        for n in names:
            if n in self.params:
                return self.params[n]
        return default

    def fit(self) -> Result:
        from .. import api

        objective = self._p("objective", default="reg:squarederror")
        supported = ("reg:squarederror", "binary:logistic",
                     "multi:softprob", "multi:softmax", "rank:pairwise")
        if objective not in supported:
            raise ValueError(f"unsupported objective {objective!r} "
                             f"(supported: {supported})")
        n_classes = 0
        if objective.startswith("multi:"):
            n_classes = int(self._p("num_class", default=0))
            if n_classes < 2:
                raise ValueError("multi:* objectives need params"
                                 "['num_class'] >= 2")
        if objective == "rank:pairwise" and self.group_column is None:
            raise ValueError("rank:pairwise needs group_column (the "
                             "per-row query-group id)")
        lr = float(self._p("eta", "learning_rate", default=0.3))
        max_depth = int(self._p("max_depth", default=6))
        lam = float(self._p("lambda", "reg_lambda", default=1.0))
        gamma = float(self._p("gamma", default=0.0))
        min_child_weight = float(self._p("min_child_weight", default=1.0))

        X, y, groups = _to_xy(self.datasets["train"], self.label_column,
                              self.group_column)
        n, n_features = X.shape

        # global quantile bin edges (shared by every worker and the model)
        bin_edges = []
        for j in range(n_features):
            qs = np.quantile(X[:, j], np.linspace(0, 1, MAX_BINS)[1:])
            bin_edges.append(np.unique(qs))
        if objective == "reg:squarederror":
            base_score = float(np.mean(y))
        elif objective == "binary:logistic":
            base_score = float(np.log(np.clip(np.mean(y), 1e-6, 1 - 1e-6)
                                      / np.clip(1 - np.mean(y), 1e-6, 1)))
        else:   # multiclass margins / rank scores start at zero
            base_score = 0.0

        ShardActor = api.remote(_GBDTShard)
        k = min(self.num_workers, n) or 1
        bounds = np.linspace(0, n, k + 1).astype(int)
        if groups is not None:
            # a query group must live whole on one shard (pairwise
            # gradients are within-group) — snap bounds forward to the
            # next group boundary.  Snapping assumes each group is one
            # contiguous run of rows; a shuffled frame would silently
            # split groups and drop their cross-shard pairs, so reject
            # it loudly.
            run_starts = 1 + int((np.asarray(groups[1:])
                                  != np.asarray(groups[:-1])).sum())
            if run_starts != len(np.unique(groups)):
                raise ValueError(
                    "rank:pairwise needs rows sorted so each query "
                    "group is contiguous (sort by the group column "
                    "first); found interleaved group ids")
            bounds = np.array(
                [0] + [self._snap_to_group(b, groups)
                       for b in bounds[1:-1]] + [n])
        shards = [ShardActor.remote(
            X[lo:hi], y[lo:hi], bin_edges, objective, base_score,
            n_classes, None if groups is None else groups[lo:hi])
            for lo, hi in zip(bounds[:-1], bounds[1:])]

        model = GBDTModel(bin_edges, objective, base_score, lr,
                          n_classes)
        metrics: Dict[str, Any] = {}
        metric_name = {"reg:squarederror": "rmse",
                       "binary:logistic": "logloss",
                       "multi:softprob": "mlogloss",
                       "multi:softmax": "mlogloss",
                       "rank:pairwise": "pairwise-error"}[objective]

        try:
            self._boost(api, shards, model, metrics, metric_name,
                        max_depth, lam, gamma, min_child_weight, lr)
        finally:
            for s in shards:
                try:
                    api.kill(s)
                except Exception:
                    pass
        ckpt = Checkpoint.from_dict({"gbdt_model": model.to_dict(),
                                     "label_column": self.label_column})
        return Result(metrics=metrics, checkpoint=ckpt)

    @staticmethod
    def _snap_to_group(b: int, groups: np.ndarray) -> int:
        n = len(groups)
        while 0 < b < n and groups[b] == groups[b - 1]:
            b += 1
        return b

    def _boost(self, api, shards, model, metrics, metric_name,
               max_depth, lam, gamma, min_child_weight, lr):
        trees_per_round = model.n_classes or 1
        for _ in range(self.num_boost_round):
            for class_k in range(trees_per_round):
                self._boost_one_tree(api, shards, model, max_depth, lam,
                                     gamma, min_child_weight, lr,
                                     class_k)
        self._final_metrics(api, shards, model, metrics, metric_name)

    def _boost_one_tree(self, api, shards, model, max_depth, lam, gamma,
                        min_child_weight, lr, class_k):
        api.get([s.start_tree.remote(class_k) for s in shards],
                timeout=300.0)
        tree = _Tree()
        root = tree.add_node()
        # node -> (sum_grad, sum_hess), computed from merged histograms
        frontier = [root]
        depth = 0
        while frontier and depth < max_depth:
            hists = api.get(
                [s.histograms.remote(frontier) for s in shards],
                timeout=300.0)
            merged = {}
            for node in frontier:
                g = sum(h[node][0] for h in hists)
                h_ = sum(h[node][1] for h in hists)
                merged[node] = (g, h_)
            splits: Dict[int, tuple] = {}
            next_frontier: List[int] = []
            for node, (g, h_) in merged.items():
                best = self._best_split(g, h_, lam, gamma,
                                        min_child_weight)
                if best is None:
                    continue
                feat, thr, _gain = best
                left = tree.add_node()
                right = tree.add_node()
                tree.feature[node] = feat
                tree.threshold_bin[node] = thr
                tree.left[node] = left
                tree.right[node] = right
                splits[node] = (feat, thr, left, right)
                next_frontier += [left, right]
            if splits:
                api.get([s.apply_splits.remote(splits) for s in shards],
                        timeout=300.0)
            frontier = next_frontier
            depth += 1
        # leaf values from the final frontier histograms
        leaves = [i for i in range(len(tree.feature))
                  if tree.feature[i] < 0]
        hists = api.get([s.histograms.remote(leaves) for s in shards],
                        timeout=300.0)
        leaf_values: Dict[int, float] = {}
        for node in leaves:
            g = sum(float(h[node][0][0].sum()) for h in hists)
            h_ = sum(float(h[node][1][0].sum()) for h in hists)
            v = -g / (h_ + lam) if (h_ + lam) > 0 else 0.0
            tree.value[node] = v
            leaf_values[node] = v
        api.get([s.finish_tree.remote(leaf_values, lr, class_k)
                 for s in shards], timeout=300.0)
        model.trees.append(tree)
        model.tree_class.append(class_k)

    def _final_metrics(self, api, shards, model, metrics, metric_name):
        parts = api.get([s.eval_metric.remote() for s in shards],
                        timeout=300.0)
        total, count = (sum(p[0] for p in parts), sum(p[1] for p in parts))
        train_metric = float(np.sqrt(total / count)) \
            if metric_name == "rmse" else total / count
        metrics[f"train-{metric_name}"] = train_metric
        for name, ds in self.datasets.items():
            if name == "train":
                continue
            Xv, yv, gv = _to_xy(ds, self.label_column,
                                self.group_column)
            margin = model.predict_margin(Xv)
            if metric_name == "rmse":
                metrics[f"{name}-rmse"] = float(
                    np.sqrt(np.mean((margin - yv) ** 2)))
            elif metric_name == "mlogloss":
                p = np.clip(_softmax_rows(margin), 1e-12, 1.0)
                rows = np.arange(len(yv))
                metrics[f"{name}-mlogloss"] = float(
                    -np.mean(np.log(p[rows, yv.astype(int)])))
            elif metric_name == "pairwise-error":
                bad, tot = _pairwise_error(margin, yv, gv)
                metrics[f"{name}-pairwise-error"] = bad / max(tot, 1)
            else:
                p = np.clip(1 / (1 + np.exp(-margin)), 1e-12, 1 - 1e-12)
                metrics[f"{name}-logloss"] = float(-np.mean(
                    yv * np.log(p) + (1 - yv) * np.log(1 - p)))

    @staticmethod
    def _best_split(g: np.ndarray, h: np.ndarray, lam: float, gamma: float,
                    min_child_weight: float):
        """xgboost gain over cumulative histograms; None if no gain."""
        G = g.sum(axis=1, keepdims=True)     # [features, 1]
        H = h.sum(axis=1, keepdims=True)
        GL = np.cumsum(g, axis=1)[:, :-1]    # left sums per threshold
        HL = np.cumsum(h, axis=1)[:, :-1]
        GR, HR = G - GL, H - HL
        valid = (HL >= min_child_weight) & (HR >= min_child_weight)
        gain = 0.5 * (GL ** 2 / (HL + lam) + GR ** 2 / (HR + lam)
                      - G ** 2 / (H + lam)) - gamma
        gain = np.where(valid, gain, -np.inf)
        j, t = np.unravel_index(np.argmax(gain), gain.shape)
        if not np.isfinite(gain[j, t]) or gain[j, t] <= 1e-12:
            return None
        return int(j), int(t), float(gain[j, t])

    @staticmethod
    def load_model(checkpoint: Checkpoint) -> GBDTModel:
        return GBDTModel.from_dict(checkpoint.to_dict()["gbdt_model"])


class LightGBMTrainer(XGBoostTrainer):
    """Reference-parity alias (`train/lightgbm/lightgbm_trainer.py`):
    lightgbm params map onto the same native histogram booster
    (num_leaves-style leaf-wise growth is approximated by depth-wise)."""

    def __init__(self, **kwargs):
        params = dict(kwargs.get("params") or {})
        if "objective" in params and params["objective"] == "regression":
            params["objective"] = "reg:squarederror"
        if params.get("objective") == "binary":
            params["objective"] = "binary:logistic"
        kwargs["params"] = params
        super().__init__(**kwargs)
