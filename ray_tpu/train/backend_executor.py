"""Drives the training gang: placement → workers → backend → train loop.

Capability mirror of the reference's `train/_internal/backend_executor.py:42`
(creates PG :137, spawns WorkerGroup :186, framework process-group setup,
per-rank `train_func` launch :314, result bubbling).  Ranks are assigned by
sorted (hostname, pid): workers on the same host get consecutive local
ranks — on TPU pods that makes world rank == slice host order, so the mesh
axes line up with ICI neighborhoods.

Elastic recovery (train/elastic.py): with an ElasticConfig, an
*unannounced* worker/node death no longer tears the gang down.  Healthy
ranks park in a deadline-bounded repair barrier (their actors survive;
only the train thread rewinds), only the dead ranks are rescheduled onto
spare capacity, every rank restores from the peer-replicated in-memory
snapshot, and the gang resumes at the snapshot step.  Deadline overrun,
a missing snapshot, or a second failure mid-repair falls back to the
legacy TrainingFailedError → full restart-from-disk path — the repair
can only ever make recovery faster, never less safe.
"""

from __future__ import annotations

import itertools
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Set

from .. import api
from ..air.checkpoint import Checkpoint
from ..core import runtime_metrics as rtm
from ..util import fault_injection as fi
from ..util import tracing
from . import elastic
from .backend import Backend, BackendConfig
from .worker_group import WorkerGroup

#: next_result poll slice: short enough that a repair never waits long
#: behind an in-flight poll on a (serial) healthy actor, long enough to
#: keep the idle RPC rate trivial
_POLL_SLICE_S = 2.0
_PROBE_TIMEOUT_S = 5.0
#: reconcile interval for the draining-node state poll — the pubsub
#: push is the primary signal now, the poll only heals a missed event
_DRAIN_POLL_INTERVAL_S = 10.0


class TrainingFailedError(RuntimeError):
    #: True on subclasses raised for PLANNED restarts (node drain):
    #: the trainer restarts without burning FailureConfig.max_failures
    planned = False


class GangDrainRestart(TrainingFailedError):
    """A gang worker sits on a draining node: restart from the latest
    checkpoint before the node departs.  Planned maintenance — exempt
    from the failure budget (the actor-migration path got this
    exemption in the drain PR; trainer attempts now match)."""
    planned = True


class _RepairAborted(RuntimeError):
    pass


class BackendExecutor:
    def __init__(self, backend_config: Optional[BackendConfig] = None,
                 num_workers: int = 1,
                 resources_per_worker: Optional[Dict[str, float]] = None,
                 placement_strategy: str = "PACK",
                 elastic_config: Optional[Any] = None):
        self.backend_config = backend_config or BackendConfig()
        self.backend: Backend = self.backend_config.backend_cls(
            self.backend_config)
        self.num_workers = num_workers
        self.resources_per_worker = resources_per_worker
        self.placement_strategy = placement_strategy
        self.elastic_config = elastic_config
        self.run_id = uuid.uuid4().hex[:8]
        self.worker_group: Optional[WorkerGroup] = None
        self.shared_env: Dict[str, Any] = {}
        # proactive drain handling: when a gang worker's node starts
        # DRAINING, finish the in-flight report (so its checkpoint is
        # registered), then restart the attempt from that checkpoint —
        # instead of dying mid-step when the node departs
        self._drain_pending: Optional[str] = None
        self._last_drain_check = 0.0
        # node-membership push state (controller `nodes` pubsub): the
        # primary death/drain signal — the state-API poll only reconciles
        self._event_lock = threading.Lock()
        self._pushed_draining: Set[str] = set()
        self._pushed_dead: Set[str] = set()
        self._subscribed_core = None
        self._node_of_worker: Dict[int, Optional[str]] = {}
        self._rank_assignments: Dict[int, Dict[str, Any]] = {}
        self._trial_name = "train"
        self._dataset_shards: Optional[List[Any]] = None
        self._elastic_args: Optional[Dict[str, Any]] = None
        self._train_blob: Optional[bytes] = None
        self._train_config: Dict[str, Any] = {}
        self._last_seen_iteration = 0
        self._repairs_done = 0

    # -- lifecycle ----------------------------------------------------------
    def start(self, *, trial_name: str = "train",
              resume_checkpoint: Optional[Checkpoint] = None,
              dataset_shards: Optional[List[Any]] = None) -> None:
        self._trial_name = trial_name
        self._dataset_shards = dataset_shards
        self.worker_group = WorkerGroup(
            self.num_workers, self.resources_per_worker,
            self.placement_strategy)
        meta = self.worker_group.metadata()
        # rank by (hostname, pid): same-host workers contiguous
        order = sorted(range(self.num_workers),
                       key=lambda i: (meta[i]["hostname"], meta[i]["pid"]))
        self.world_ranks = {worker_idx: rank
                            for rank, worker_idx in enumerate(order)}
        self._node_of_worker = {i: meta[i].get("node_id")
                                for i in range(self.num_workers)}
        local_counters: Dict[str, Any] = {}
        node_ids: Dict[str, int] = {}
        ckpt_bytes = (resume_checkpoint.to_bytes()
                      if resume_checkpoint else None)
        ec = self.elastic_config
        self._elastic_args = None
        if ec is not None:
            self._elastic_args = {
                "run_id": f"{trial_name}:{self.run_id}",
                "interval": ec.snapshot_interval_steps,
                "keep": ec.keep_snapshots}
        refs = []
        for worker_idx, w in enumerate(self.worker_group.workers):
            host = meta[worker_idx]["hostname"]
            local_rank = local_counters.setdefault(
                host, itertools.count()).__next__()
            node_rank = node_ids.setdefault(host, len(node_ids))
            self._rank_assignments[worker_idx] = {
                "local_rank": local_rank, "node_rank": node_rank}
            refs.append(w.init_session.remote(
                world_rank=self.world_ranks[worker_idx],
                local_rank=local_rank,
                world_size=self.num_workers,
                node_rank=node_rank,
                trial_name=trial_name,
                checkpoint_bytes=ckpt_bytes,
                dataset_shard=(dataset_shards[self.world_ranks[worker_idx]]
                               if dataset_shards else None),
                elastic=self._elastic_args))
        api.get(refs, timeout=120.0)
        self._subscribe_node_events()
        self.backend.on_start(self.worker_group, self)
        setup = self.backend.worker_setup_fn(self)
        if setup is not None:
            self.worker_group.execute(setup)

    def start_training(self, train_fn: Callable,
                       config: Optional[Dict[str, Any]] = None) -> None:
        from ..core.serialization import dumps_function
        self._train_blob = dumps_function(train_fn)
        self._train_config = config or {}
        api.get([w.start_training.remote(self._train_blob,
                                         self._train_config)
                 for w in self.worker_group.workers], timeout=120.0)

    # -- node-membership push ------------------------------------------------
    def _subscribe_node_events(self) -> None:
        try:
            from ..core.driver import get_global_core
            core = get_global_core()
            if core is None:
                return
            core.subscribe_node_events(self._on_node_event)
            self._subscribed_core = core
        except Exception:
            self._subscribed_core = None  # poll reconcile still covers us

    def _unsubscribe_node_events(self) -> None:
        core, self._subscribed_core = self._subscribed_core, None
        if core is not None:
            try:
                core.unsubscribe_node_events(self._on_node_event)
            except Exception:
                pass

    def _on_node_event(self, ev: Dict[str, Any]) -> None:
        # runs on the driver IO loop: record and return, never block
        event, nid = ev.get("event"), ev.get("node_id")
        if not nid:
            return
        with self._event_lock:
            if event == "draining":
                self._pushed_draining.add(nid)
            elif event == "dead":
                self._pushed_dead.add(nid)
            # "suspect" is deliberately NOT a repair trigger: the node's
            # controller link is down but peers still reach it, its rank
            # is alive and stepping (collectives run peer-to-peer), and
            # it rejoins intact inside the grace budget — tearing the
            # gang down for a gray failure is exactly the over-reaction
            # the quarantine exists to prevent.  A suspect that really
            # dies escalates to a "dead" event, which repairs as usual.

    def _gang_nodes(self) -> Set[str]:
        return {n for n in self._node_of_worker.values() if n}

    def _gang_on_draining_node(self) -> Optional[str]:
        """Node id of a draining node hosting one of our gang actors, or
        None.  Pubsub-pushed state answers instantly; the throttled
        state-API poll (every ~10 s) only reconciles a missed event."""
        gang = self._gang_nodes()
        with self._event_lock:
            hit = next((n for n in self._pushed_draining if n in gang),
                       None)
        if hit is not None:
            return hit
        now = time.monotonic()
        if now - self._last_drain_check < _DRAIN_POLL_INTERVAL_S:
            return None
        self._last_drain_check = now
        try:
            from .. import state
            draining = {n["id"] for n in state.list_nodes()
                        if n.get("alive") and n.get("draining")}
            hit = next((n for n in draining if n in gang), None)
            if hit is not None:
                return hit
            if not draining:
                return None
            # gang metadata may predate a migration: fall back to the
            # actor table the old poll used
            aids = {w._actor_id for w in self.worker_group.workers}
            for row in state.list_actors():
                if row.get("actor_id") in aids \
                        and row.get("node_id") in draining:
                    return row["node_id"]
        except Exception:
            return None
        return None

    def _gang_node_died(self) -> bool:
        with self._event_lock:
            return bool(self._pushed_dead & self._gang_nodes())

    # -- results -------------------------------------------------------------
    def next_results(self, timeout_s: float = 60.0):
        """One report from every rank (ordered by world rank), or None when
        all ranks finished.  Raises TrainingFailedError on worker failure
        (GangDrainRestart for planned drains).  Polls in short slices so
        an elastic repair is never stuck behind a long in-flight poll on
        a healthy rank's serial actor queue."""
        deadline = time.monotonic() + timeout_s
        while True:
            if self._drain_pending is not None:
                # the previous report (and its checkpoint) has been
                # consumed by the trainer — restart NOW from it, before
                # the draining node kills the gang mid-step
                nid = self._drain_pending
                self._drain_pending = None
                raise GangDrainRestart(
                    f"gang worker on draining node {nid[:12]}; restarting "
                    f"from the latest checkpoint before the node departs")
            if self.elastic_config is not None and self._gang_node_died():
                # pubsub beat the RPC failure to us: repair proactively
                if not self._try_repair():
                    raise TrainingFailedError(
                        "gang node died and elastic repair failed")
                continue
            poll = min(_POLL_SLICE_S, max(0.2, deadline - time.monotonic()))
            refs = [w.next_result.remote(poll)
                    for w in self.worker_group.workers]
            try:
                results = api.get(refs, timeout=poll + 60.0)
            except Exception as e:
                if self._try_repair():
                    continue
                raise TrainingFailedError(
                    f"worker lost mid-training: {e}") from e
            if all(r is None for r in results):
                return None
            if all(r in (None, "__timeout__") for r in results) \
                    and time.monotonic() < deadline:
                continue  # nothing reported yet: poll the next slice
            self._drain_pending = self._gang_on_draining_node()
            if any(r is None for r in results):
                # some ranks done, some not: drain the stragglers next call
                results = [r if r is not None else "__timeout__"
                           for r in results]
            by_rank = [None] * self.num_workers
            for worker_idx, r in enumerate(results):
                by_rank[self.world_ranks[worker_idx]] = r
                if isinstance(r, dict):
                    self._last_seen_iteration = max(
                        self._last_seen_iteration, r.get("iteration", 0))
            return by_rank

    # -- elastic repair ------------------------------------------------------
    def _try_repair(self) -> bool:
        """Fast gang repair after an unannounced death.  True: the gang
        is training again from the newest common snapshot.  False: the
        caller must take the legacy full-restart path."""
        ec = self.elastic_config
        if ec is None or self.worker_group is None \
                or self._train_blob is None:
            return False
        if self._repairs_done >= ec.max_repairs:
            return False
        t0 = time.monotonic()
        t0_wall = time.time()
        outcome, step = "fallback", -1
        try:
            step = self._repair_once(t0 + ec.repair_deadline_s)
            self._repairs_done += 1
            outcome = "repaired"
            return True
        except Exception:
            return False
        finally:
            rtm.TRAIN_REPAIRS.inc(tags={"outcome": outcome})
            rtm.TRAIN_REPAIR_DURATION.observe(
                time.monotonic() - t0, tags={"outcome": outcome})
            tracing.record_span(
                f"train_repair::{self._trial_name}", "train",
                t0_wall, time.time(), outcome=outcome, step=step,
                run_id=self.run_id)
            # flight-recorder bundle at the controller: the death that
            # caused this repair plus the repair itself, capturable
            # after the fact (rate-limited controller-side; best effort)
            try:
                from ..core.driver import get_global_core
                get_global_core().controller.notify("debug_capture", {
                    "trigger": "elastic_repair",
                    "reason": f"{outcome} at step {step} "
                              f"({self._trial_name})",
                    "meta": {"run_id": self.run_id}})
            except Exception:
                pass

    def _check_deadline(self, deadline: float, phase: str) -> float:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise _RepairAborted(f"repair deadline overrun at {phase}")
        return remaining

    def _repair_once(self, deadline: float) -> int:
        ec = self.elastic_config
        wg = self.worker_group
        # 1. probe the gang: which ranks are gone?  (A dead actor's call
        # fails fast — the conn is reset and the controller knows.)
        dead: List[int] = []
        probes = [(i, w.metadata.remote()) for i, w in
                  enumerate(wg.workers)]
        for i, ref in probes:
            try:
                api.get([ref], timeout=min(
                    _PROBE_TIMEOUT_S,
                    self._check_deadline(deadline, "probe")))
            except Exception:
                dead.append(i)
        if not dead:
            raise _RepairAborted("no dead rank found")
        # 2. newest step every rank holds a replicated snapshot for
        run_id = (self._elastic_args or {}).get("run_id", "")
        snaps = elastic.load_gang_snapshots(run_id, self.num_workers)
        step = elastic.pick_common_step(snaps, self.num_workers)
        if step is None:
            raise _RepairAborted("no common replicated snapshot step")
        if fi.ACTIVE is not None:
            act = fi.ACTIVE.point(elastic.RESTORE_SITE,
                                  f"{run_id}:{step}")
            if act is not None:
                if act["action"] in ("delay", "latency"):
                    time.sleep(max(0.0, act["delay_s"]))
                else:
                    raise _RepairAborted("chaos: repair restore failed")
        # 3. fetch every rank's shard (the dead ranks' shards survive on
        # their ring-neighbor peers — that is the whole point)
        blobs: Dict[int, bytes] = {}
        for worker_idx in range(self.num_workers):
            rank = self.world_ranks[worker_idx]
            entry = elastic.snapshot_at(snaps[rank], step)
            blobs[worker_idx] = elastic.fetch_snapshot_bytes(
                entry, timeout=min(20.0, self._check_deadline(
                    deadline, "restore")))
        # 4. park the healthy ranks: rewind their sessions in place —
        # actors stay up, no placement work, no restart budget burned
        for i, w in enumerate(wg.workers):
            if i in dead:
                continue
            remaining = self._check_deadline(deadline, "park")
            ok = api.get([w.reset_for_repair.remote(
                blobs[i], step,
                join_timeout_s=min(10.0, remaining))],
                timeout=remaining + 10.0)[0]
            if not ok:
                raise _RepairAborted(
                    f"rank {self.world_ranks[i]} refused to park")
        # 5. reschedule ONLY the dead ranks (outside the PG — their
        # bundles sit on the dead node; spare capacity takes them)
        init_refs = []
        for i in dead:
            w = wg.spawn_replacement(i)
            asn = self._rank_assignments.get(i, {})
            rank = self.world_ranks[i]
            init_refs.append(w.init_session.remote(
                world_rank=rank,
                local_rank=asn.get("local_rank", 0),
                world_size=self.num_workers,
                node_rank=asn.get("node_rank", 0),
                trial_name=self._trial_name,
                checkpoint_bytes=blobs[i],
                dataset_shard=(self._dataset_shards[rank]
                               if self._dataset_shards else None),
                elastic=self._elastic_args,
                start_iteration=step))
        api.get(init_refs, timeout=self._check_deadline(deadline, "spawn"))
        # 6. refresh the gang's node map + consume the death flags
        try:
            meta = wg.metadata()
            self._node_of_worker = {i: meta[i].get("node_id")
                                    for i in range(self.num_workers)}
        except Exception as e:
            raise _RepairAborted(f"post-repair metadata probe: {e}")
        with self._event_lock:
            self._pushed_dead &= self._gang_nodes()
        # 7. re-run the backend rendezvous (process groups, mesh env)
        self._check_deadline(deadline, "rendezvous")
        self.backend.on_start(wg, self)
        setup = self.backend.worker_setup_fn(self)
        if setup is not None:
            wg.execute(setup)
        # 8. resume every rank from the snapshot step
        api.get([w.start_training.remote(self._train_blob,
                                         self._train_config)
                 for w in wg.workers],
                timeout=self._check_deadline(deadline, "resume") + 30.0)
        lost = max(0, self._last_seen_iteration - step)
        rtm.TRAIN_LOST_STEPS.inc(lost)
        self._last_seen_iteration = step
        return step

    # -- teardown ------------------------------------------------------------
    def finish(self) -> None:
        try:
            api.get([w.finish.remote()
                     for w in self.worker_group.workers], timeout=600.0)
        except Exception as e:
            raise TrainingFailedError(str(e)) from e

    def shutdown(self) -> None:
        self._unsubscribe_node_events()
        if self.worker_group is not None:
            try:
                self.backend.on_shutdown(self.worker_group, self)
            finally:
                self.worker_group.shutdown()
                self.worker_group = None
        # free the snapshot objects AFTER the gang is gone: a still-live
        # snapshotter could otherwise re-register behind the sweep and
        # leak its peer-pinned object
        if self._elastic_args is not None:
            try:
                elastic.cleanup_run(self._elastic_args["run_id"],
                                    self.num_workers)
            except Exception:
                pass
