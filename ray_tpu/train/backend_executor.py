"""Drives the training gang: placement → workers → backend → train loop.

Capability mirror of the reference's `train/_internal/backend_executor.py:42`
(creates PG :137, spawns WorkerGroup :186, framework process-group setup,
per-rank `train_func` launch :314, result bubbling).  Ranks are assigned by
sorted (hostname, pid): workers on the same host get consecutive local
ranks — on TPU pods that makes world rank == slice host order, so the mesh
axes line up with ICI neighborhoods.
"""

from __future__ import annotations

import itertools
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

from .. import api
from ..air.checkpoint import Checkpoint
from .backend import Backend, BackendConfig
from .worker_group import WorkerGroup


class TrainingFailedError(RuntimeError):
    pass


class BackendExecutor:
    def __init__(self, backend_config: Optional[BackendConfig] = None,
                 num_workers: int = 1,
                 resources_per_worker: Optional[Dict[str, float]] = None,
                 placement_strategy: str = "PACK"):
        self.backend_config = backend_config or BackendConfig()
        self.backend: Backend = self.backend_config.backend_cls(
            self.backend_config)
        self.num_workers = num_workers
        self.resources_per_worker = resources_per_worker
        self.placement_strategy = placement_strategy
        self.run_id = uuid.uuid4().hex[:8]
        self.worker_group: Optional[WorkerGroup] = None
        self.shared_env: Dict[str, Any] = {}
        # proactive drain handling: when a gang worker's node starts
        # DRAINING, finish the in-flight report (so its checkpoint is
        # registered), then restart the attempt from that checkpoint —
        # instead of dying mid-step when the node departs
        self._drain_pending: Optional[str] = None
        self._last_drain_check = 0.0

    # -- lifecycle ----------------------------------------------------------
    def start(self, *, trial_name: str = "train",
              resume_checkpoint: Optional[Checkpoint] = None,
              dataset_shards: Optional[List[Any]] = None) -> None:
        self.worker_group = WorkerGroup(
            self.num_workers, self.resources_per_worker,
            self.placement_strategy)
        meta = self.worker_group.metadata()
        # rank by (hostname, pid): same-host workers contiguous
        order = sorted(range(self.num_workers),
                       key=lambda i: (meta[i]["hostname"], meta[i]["pid"]))
        self.world_ranks = {worker_idx: rank
                            for rank, worker_idx in enumerate(order)}
        local_counters: Dict[str, Any] = {}
        node_ids: Dict[str, int] = {}
        ckpt_bytes = (resume_checkpoint.to_bytes()
                      if resume_checkpoint else None)
        refs = []
        for worker_idx, w in enumerate(self.worker_group.workers):
            host = meta[worker_idx]["hostname"]
            local_rank = local_counters.setdefault(
                host, itertools.count()).__next__()
            node_rank = node_ids.setdefault(host, len(node_ids))
            refs.append(w.init_session.remote(
                world_rank=self.world_ranks[worker_idx],
                local_rank=local_rank,
                world_size=self.num_workers,
                node_rank=node_rank,
                trial_name=trial_name,
                checkpoint_bytes=ckpt_bytes,
                dataset_shard=(dataset_shards[self.world_ranks[worker_idx]]
                               if dataset_shards else None)))
        api.get(refs, timeout=120.0)
        self.backend.on_start(self.worker_group, self)
        setup = self.backend.worker_setup_fn(self)
        if setup is not None:
            self.worker_group.execute(setup)

    def start_training(self, train_fn: Callable,
                       config: Optional[Dict[str, Any]] = None) -> None:
        from ..core.serialization import dumps_function
        blob = dumps_function(train_fn)
        api.get([w.start_training.remote(blob, config or {})
                 for w in self.worker_group.workers], timeout=120.0)

    def _gang_on_draining_node(self) -> Optional[str]:
        """Node id of a draining node hosting one of our gang actors, or
        None.  Throttled — one state-API round trip every ~2 s."""
        now = time.monotonic()
        if now - self._last_drain_check < 2.0:
            return None
        self._last_drain_check = now
        try:
            from .. import state
            draining = {n["id"] for n in state.list_nodes()
                        if n.get("alive") and n.get("draining")}
            if not draining:
                return None
            aids = {w._actor_id for w in self.worker_group.workers}
            for row in state.list_actors():
                if row.get("actor_id") in aids \
                        and row.get("node_id") in draining:
                    return row["node_id"]
        except Exception:
            return None
        return None

    def next_results(self, timeout_s: float = 60.0):
        """One report from every rank (ordered by world rank), or None when
        all ranks finished.  Raises TrainingFailedError on worker failure."""
        if self._drain_pending is not None:
            # the previous report (and its checkpoint) has been consumed
            # by the trainer — restart NOW from it, before the draining
            # node kills the gang mid-step
            nid = self._drain_pending
            self._drain_pending = None
            raise TrainingFailedError(
                f"gang worker on draining node {nid[:12]}; restarting "
                f"from the latest checkpoint before the node departs")
        refs = [w.next_result.remote(timeout_s)
                for w in self.worker_group.workers]
        try:
            results = api.get(refs, timeout=timeout_s + 60.0)
        except Exception as e:
            raise TrainingFailedError(f"worker lost mid-training: {e}") from e
        if all(r is None for r in results):
            return None
        self._drain_pending = self._gang_on_draining_node()
        if any(r is None for r in results):
            # some ranks done, some not: drain the stragglers next call
            results = [r if r is not None else "__timeout__"
                       for r in results]
        by_rank = [None] * self.num_workers
        for worker_idx, r in enumerate(results):
            by_rank[self.world_ranks[worker_idx]] = r
        return by_rank

    def finish(self) -> None:
        try:
            api.get([w.finish.remote()
                     for w in self.worker_group.workers], timeout=600.0)
        except Exception as e:
            raise TrainingFailedError(str(e)) from e

    def shutdown(self) -> None:
        if self.worker_group is not None:
            try:
                self.backend.on_shutdown(self.worker_group, self)
            finally:
                self.worker_group.shutdown()
                self.worker_group = None
