// Minimal msgpack codec for the ray_tpu wire protocol.
//
// The control plane frames msgpack arrays [seq, kind, method, data]
// (ray_tpu/core/rpc.py).  This header implements exactly the subset the
// protocol uses — nil/bool/int/float64/str/bin/array/map(string keys) —
// with no external dependencies, playing the role the vendored
// msgpack-c headers play for the reference's C++ worker (cpp/ in
// /root/reference, xlang data boundary).
#pragma once

#include <cstdint>
#include <cstring>
#include <map>
#include <stdexcept>
#include <string>
#include <vector>

namespace ray_tpu {
namespace msgpack_lite {

class Value {
 public:
  enum class Type { Nil, Bool, Int, Float, Str, Bin, Array, Map };

  Type type = Type::Nil;
  bool b = false;
  int64_t i = 0;
  double f = 0.0;
  std::string s;                  // Str and Bin payloads
  std::vector<Value> arr;
  std::map<std::string, Value> map;

  Value() = default;
  static Value Nil() { return Value(); }
  static Value Of(bool v) { Value x; x.type = Type::Bool; x.b = v; return x; }
  static Value Of(int64_t v) { Value x; x.type = Type::Int; x.i = v; return x; }
  static Value Of(int v) { return Of(static_cast<int64_t>(v)); }
  static Value Of(double v) { Value x; x.type = Type::Float; x.f = v; return x; }
  static Value Str(std::string v) {
    Value x; x.type = Type::Str; x.s = std::move(v); return x;
  }
  static Value Bin(std::string v) {
    Value x; x.type = Type::Bin; x.s = std::move(v); return x;
  }
  static Value Arr(std::vector<Value> v) {
    Value x; x.type = Type::Array; x.arr = std::move(v); return x;
  }
  static Value MapOf(std::map<std::string, Value> v) {
    Value x; x.type = Type::Map; x.map = std::move(v); return x;
  }

  bool is_nil() const { return type == Type::Nil; }
  int64_t as_int() const {
    if (type == Type::Int) return i;
    if (type == Type::Float) return static_cast<int64_t>(f);
    throw std::runtime_error("msgpack: not an int");
  }
  double as_float() const {
    if (type == Type::Float) return f;
    if (type == Type::Int) return static_cast<double>(i);
    throw std::runtime_error("msgpack: not a float");
  }
  const std::string& as_str() const {
    if (type != Type::Str && type != Type::Bin)
      throw std::runtime_error("msgpack: not a str/bin");
    return s;
  }
  const Value& at(const std::string& key) const {
    auto it = map.find(key);
    if (it == map.end()) throw std::runtime_error("msgpack: no key " + key);
    return it->second;
  }
  bool has(const std::string& key) const { return map.count(key) > 0; }
};

// ----------------------------------------------------------------- encode
inline void PackTo(const Value& v, std::string* out);

inline void put_u8(std::string* o, uint8_t b) { o->push_back(char(b)); }
inline void put_be(std::string* o, uint64_t v, int bytes) {
  for (int i = bytes - 1; i >= 0; --i) o->push_back(char((v >> (8 * i)) & 0xff));
}

inline void PackTo(const Value& v, std::string* out) {
  using T = Value::Type;
  switch (v.type) {
    case T::Nil: put_u8(out, 0xc0); break;
    case T::Bool: put_u8(out, v.b ? 0xc3 : 0xc2); break;
    case T::Int: {
      int64_t x = v.i;
      if (x >= 0 && x < 128) put_u8(out, uint8_t(x));
      else if (x < 0 && x >= -32) put_u8(out, uint8_t(x));
      else { put_u8(out, 0xd3); put_be(out, uint64_t(x), 8); }
      break;
    }
    case T::Float: {
      put_u8(out, 0xcb);
      uint64_t bits;
      static_assert(sizeof(bits) == sizeof(v.f), "double size");
      std::memcpy(&bits, &v.f, 8);
      put_be(out, bits, 8);
      break;
    }
    case T::Str: {
      size_t n = v.s.size();
      if (n < 32) put_u8(out, uint8_t(0xa0 | n));
      else if (n < 256) { put_u8(out, 0xd9); put_u8(out, uint8_t(n)); }
      else if (n < 65536) { put_u8(out, 0xda); put_be(out, n, 2); }
      else { put_u8(out, 0xdb); put_be(out, n, 4); }
      out->append(v.s);
      break;
    }
    case T::Bin: {
      size_t n = v.s.size();
      if (n < 256) { put_u8(out, 0xc4); put_u8(out, uint8_t(n)); }
      else if (n < 65536) { put_u8(out, 0xc5); put_be(out, n, 2); }
      else { put_u8(out, 0xc6); put_be(out, n, 4); }
      out->append(v.s);
      break;
    }
    case T::Array: {
      size_t n = v.arr.size();
      if (n < 16) put_u8(out, uint8_t(0x90 | n));
      else if (n < 65536) { put_u8(out, 0xdc); put_be(out, n, 2); }
      else { put_u8(out, 0xdd); put_be(out, n, 4); }
      for (const auto& e : v.arr) PackTo(e, out);
      break;
    }
    case T::Map: {
      size_t n = v.map.size();
      if (n < 16) put_u8(out, uint8_t(0x80 | n));
      else if (n < 65536) { put_u8(out, 0xde); put_be(out, n, 2); }
      else { put_u8(out, 0xdf); put_be(out, n, 4); }
      for (const auto& kv : v.map) {
        PackTo(Value::Str(kv.first), out);
        PackTo(kv.second, out);
      }
      break;
    }
  }
}

inline std::string Pack(const Value& v) {
  std::string out;
  PackTo(v, &out);
  return out;
}

// ----------------------------------------------------------------- decode
class Reader {
 public:
  Reader(const char* data, size_t size) : p_(data), end_(data + size) {}

  Value Next() {
    uint8_t tag = u8();
    if (tag < 0x80) return Value::Of(int64_t(tag));            // pos fixint
    if (tag >= 0xe0) return Value::Of(int64_t(int8_t(tag)));   // neg fixint
    if ((tag & 0xf0) == 0x90) return array(tag & 0x0f);        // fixarray
    if ((tag & 0xf0) == 0x80) return mapv(tag & 0x0f);         // fixmap
    if ((tag & 0xe0) == 0xa0) return str(tag & 0x1f);          // fixstr
    switch (tag) {
      case 0xc0: return Value::Nil();
      case 0xc2: return Value::Of(false);
      case 0xc3: return Value::Of(true);
      case 0xc4: return bin(u8());
      case 0xc5: return bin(be(2));
      case 0xc6: return bin(be(4));
      case 0xca: {  // float32
        uint32_t bits = uint32_t(be(4));
        float f;
        std::memcpy(&f, &bits, 4);
        return Value::Of(double(f));
      }
      case 0xcb: {  // float64
        uint64_t bits = be(8);
        double f;
        std::memcpy(&f, &bits, 8);
        return Value::Of(f);
      }
      case 0xcc: return Value::Of(int64_t(u8()));
      case 0xcd: return Value::Of(int64_t(be(2)));
      case 0xce: return Value::Of(int64_t(be(4)));
      case 0xcf: return Value::Of(int64_t(be(8)));   // uint64 (truncates >2^63)
      case 0xd0: return Value::Of(int64_t(int8_t(u8())));
      case 0xd1: return Value::Of(int64_t(int16_t(be(2))));
      case 0xd2: return Value::Of(int64_t(int32_t(be(4))));
      case 0xd3: return Value::Of(int64_t(be(8)));
      case 0xd9: return str(u8());
      case 0xda: return str(be(2));
      case 0xdb: return str(be(4));
      case 0xdc: return array(be(2));
      case 0xdd: return array(be(4));
      case 0xde: return mapv(be(2));
      case 0xdf: return mapv(be(4));
      default:
        throw std::runtime_error("msgpack: unsupported tag");
    }
  }

 private:
  uint8_t u8() {
    if (p_ >= end_) throw std::runtime_error("msgpack: truncated");
    return uint8_t(*p_++);
  }
  uint64_t be(int bytes) {
    uint64_t v = 0;
    for (int i = 0; i < bytes; ++i) v = (v << 8) | u8();
    return v;
  }
  std::string take(size_t n) {
    if (size_t(end_ - p_) < n) throw std::runtime_error("msgpack: truncated");
    std::string s(p_, n);
    p_ += n;
    return s;
  }
  Value str(size_t n) { return Value::Str(take(n)); }
  Value bin(size_t n) { return Value::Bin(take(n)); }
  Value array(size_t n) {
    std::vector<Value> out;
    out.reserve(n);
    for (size_t i = 0; i < n; ++i) out.push_back(Next());
    return Value::Arr(std::move(out));
  }
  Value mapv(size_t n) {
    std::map<std::string, Value> out;
    for (size_t i = 0; i < n; ++i) {
      Value k = Next();
      out[k.as_str()] = Next();
    }
    return Value::MapOf(std::move(out));
  }

  const char* p_;
  const char* end_;
};

inline Value Unpack(const std::string& buf) {
  Reader r(buf.data(), buf.size());
  return r.Next();
}

}  // namespace msgpack_lite
}  // namespace ray_tpu
