"""Native C++ language surface: driver client (ray_tpu_client.cc),
worker-side task/actor execution (worker_main.cc + task_api.h), and
on-demand builds (build.py)."""
