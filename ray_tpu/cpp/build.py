"""On-demand builds for the native C++ worker and example task library.

Same pattern as the object store's auto-compile
(core/object_store/client.py::_ensure_built): g++ straight from the
in-tree sources, mtime-checked, atomic rename."""

from __future__ import annotations

import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_lock = threading.Lock()


def _build(output: str, srcs: list, extra: list) -> str:
    out_path = os.path.join(_DIR, output)
    src_paths = [os.path.join(_DIR, s) for s in srcs]
    hdrs = [os.path.join(_DIR, h)
            for h in ("msgpack_lite.h", "task_api.h")
            if os.path.exists(os.path.join(_DIR, h))]
    with _lock:
        newest = max(os.path.getmtime(p) for p in src_paths + hdrs)
        if not os.path.exists(out_path) \
                or os.path.getmtime(out_path) < newest:
            tmp = out_path + f".tmp.{os.getpid()}"
            subprocess.run(
                ["g++", "-O2", "-std=c++17", *extra, "-o", tmp, *src_paths],
                check=True, capture_output=True, cwd=_DIR)
            os.replace(tmp, out_path)
    return out_path


def ensure_worker_built() -> str:
    """The native worker binary the nodelet execs for lang="cpp" leases."""
    return _build("ray_tpu_cpp_worker", ["worker_main.cc"], ["-ldl"])


def ensure_example_lib_built() -> str:
    """The example/test task library (task_api.h fixture)."""
    return _build("libexample.so", ["example_tasks.cc"],
                  ["-shared", "-fPIC"])
