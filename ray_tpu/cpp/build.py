"""On-demand builds for the native C++ worker and example task library.

Same pattern as the object store's auto-compile
(core/object_store/client.py::_ensure_built): g++ straight from the
in-tree sources, mtime-checked, atomic rename."""

from __future__ import annotations

import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_lock = threading.Lock()


_healthy_cache: set = set()


def _artifact_healthy(path: str) -> bool:
    """A fresh-by-mtime artifact can still be unusable: a prebuilt
    binary seeded from another image fails in the dynamic loader
    (GLIBC version mismatch) before main.  Probe cheaply — dlopen for
    shared libs, ``--selftest`` (prints and exits pre-connect) for the
    worker executable — and rebuild on failure.  Probed once per
    process (ensure_worker_built runs on every cpp worker spawn)."""
    if path in _healthy_cache:
        return True
    try:
        if path.endswith(".so"):
            import ctypes
            ctypes.CDLL(path)
            _healthy_cache.add(path)
            return True
        r = subprocess.run([path, "--selftest"], capture_output=True,
                           timeout=10)
        if r.returncode == 0:
            _healthy_cache.add(path)
        return r.returncode == 0
    except Exception:
        return False


def _build(output: str, srcs: list, extra: list) -> str:
    out_path = os.path.join(_DIR, output)
    src_paths = [os.path.join(_DIR, s) for s in srcs]
    hdrs = [os.path.join(_DIR, h)
            for h in ("msgpack_lite.h", "task_api.h")
            if os.path.exists(os.path.join(_DIR, h))]
    with _lock:
        newest = max(os.path.getmtime(p) for p in src_paths + hdrs)
        if not os.path.exists(out_path) \
                or os.path.getmtime(out_path) < newest \
                or not _artifact_healthy(out_path):
            tmp = out_path + f".tmp.{os.getpid()}"
            # libraries (-ldl) must follow the sources: this image's ld
            # defaults to --as-needed and drops libs named before any
            # object that references them
            subprocess.run(
                ["g++", "-O2", "-std=c++17", *extra, "-o", tmp,
                 *src_paths, "-ldl"],
                check=True, capture_output=True, cwd=_DIR)
            os.replace(tmp, out_path)
    return out_path


def ensure_worker_built() -> str:
    """The native worker binary the nodelet execs for lang="cpp" leases."""
    return _build("ray_tpu_cpp_worker", ["worker_main.cc"], [])


def ensure_example_lib_built() -> str:
    """The example/test task library (task_api.h fixture)."""
    return _build("libexample.so", ["example_tasks.cc"],
                  ["-shared", "-fPIC"])
