// Native C++ worker: task/actor EXECUTION in C++.
//
// The reference executes tasks inside C++ worker processes
// (/root/reference/cpp/src/ray/runtime/task/task_executor.cc with the
// user API in cpp/include/ray/api/); this is that capability for the
// TPU-native runtime.  The binary speaks the exact worker wire protocol
// of ray_tpu/core/worker_runtime.py — register_worker with the nodelet,
// then serve push_task / create_actor / push_actor_task / ping / exit
// frames — so the nodelet leases it like any Python worker (routed by
// TaskSpec lang=="cpp", nodelet._spawn_cpp_worker).
//
// Execution model: user code lives in a shared library that implements
// the fixed ABI of task_api.h (ray_tpu_cpp_invoke / ray_tpu_cpp_actor_*);
// function descriptors are "path/to/lib.so:name".  Values cross the
// language boundary in the RTX1 xlang object format (msgpack behind a
// 4-byte magic, core/serialization.py serialize_xlang) — the same
// msgpack-typed data restriction as the reference's cross-language calls.
// The worker is single-threaded: tasks execute inline in the frame loop
// (max_concurrency==1 semantics; per-connection FIFO gives per-caller
// actor ordering).
//
// Object store access is direct: the worker links the rts_* C API of
// store.cc (dlopened libtpustore.so) and reads argument objects /
// writes large returns straight in shared memory; missing objects are
// pulled via the nodelet ("pull" RPC) exactly like the Python worker.

#include <arpa/inet.h>
#include <dlfcn.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <memory>
#include <set>
#include <string>
#include <vector>

#include "msgpack_lite.h"

using ray_tpu::msgpack_lite::Pack;
using ray_tpu::msgpack_lite::Unpack;
using Val = ray_tpu::msgpack_lite::Value;

namespace {

constexpr int kRequest = 0, kReply = 1, kError = 2, kNotify = 3;
constexpr int kArgValue = 0, kArgRef = 1;
const char kXMagic[4] = {'R', 'T', 'X', '1'};

// ---------------------------------------------------------------- store API
struct StoreApi {
  void* handle = nullptr;            // rts segment handle
  uint8_t* base = nullptr;           // mapped segment base
  int64_t (*create)(void*, const uint8_t*, uint64_t) = nullptr;
  int (*seal)(void*, const uint8_t*) = nullptr;
  int (*abort_)(void*, const uint8_t*) = nullptr;
  int (*get)(void*, const uint8_t*, int64_t, uint64_t*, uint64_t*) = nullptr;
  int (*release)(void*, const uint8_t*) = nullptr;
};

// Handle layout prefix — must match store.cc's Handle {fd, base, size, hdr}
// (same prefix-view trick transfer.cc uses for zero-copy sends).
struct HandleView {
  int fd;
  uint8_t* base;
};

StoreApi OpenStore(const std::string& lib_path, const std::string& seg_path,
                   std::string* err) {
  StoreApi api;
  void* lib = dlopen(lib_path.c_str(), RTLD_NOW | RTLD_GLOBAL);
  if (!lib) {
    *err = std::string("dlopen libtpustore: ") + dlerror();
    return api;
  }
  void* (*open_fn)(const char*) =
      (void* (*)(const char*))dlsym(lib, "rts_open");
  api.create = (int64_t (*)(void*, const uint8_t*, uint64_t))
      dlsym(lib, "rts_create");
  api.seal = (int (*)(void*, const uint8_t*))dlsym(lib, "rts_seal");
  api.abort_ = (int (*)(void*, const uint8_t*))dlsym(lib, "rts_abort");
  api.get = (int (*)(void*, const uint8_t*, int64_t, uint64_t*, uint64_t*))
      dlsym(lib, "rts_get");
  api.release = (int (*)(void*, const uint8_t*))dlsym(lib, "rts_release");
  if (!open_fn || !api.create || !api.seal || !api.get || !api.release) {
    *err = "libtpustore missing rts_* symbols";
    return api;
  }
  api.handle = open_fn(seg_path.c_str());
  if (!api.handle) {
    *err = "rts_open failed for " + seg_path;
    return api;
  }
  api.base = ((HandleView*)api.handle)->base;
  return api;
}

// ----------------------------------------------------------------- sockets
bool WriteAll(int fd, const char* p, size_t n) {
  while (n) {
    ssize_t k = ::write(fd, p, n);
    if (k <= 0) {
      if (k < 0 && (errno == EINTR || errno == EAGAIN)) continue;
      return false;
    }
    p += k;
    n -= (size_t)k;
  }
  return true;
}

struct Conn {
  int fd = -1;
  std::string rbuf;               // partial-frame accumulator
  int64_t next_seq = 0;           // outbound request seqs
  bool dead = false;

  bool SendFrame(const std::string& payload) {
    char head[4];
    uint32_t n = (uint32_t)payload.size();
    memcpy(head, &n, 4);          // little-endian on every target we run on
    if (!WriteAll(fd, head, 4) || !WriteAll(fd, payload.data(), n)) {
      dead = true;
      return false;
    }
    return true;
  }

  bool Send(int64_t seq, int kind, const std::string& method,
            const Val& data) {
    Val frame = Val::Arr({Val::Of(seq), Val::Of((int64_t)kind),
                          Val::Str(method), data});
    return SendFrame(Pack(frame));
  }

  // Pop one complete frame out of rbuf, if present.
  bool PopFrame(Val* out) {
    if (rbuf.size() < 4) return false;
    uint32_t n;
    memcpy(&n, rbuf.data(), 4);
    if (rbuf.size() < 4 + (size_t)n) return false;
    *out = Unpack(rbuf.substr(4, n));
    rbuf.erase(0, 4 + (size_t)n);
    return true;
  }

  // Blocking read of at least one byte into rbuf.
  bool Fill() {
    char buf[65536];
    ssize_t k = ::read(fd, buf, sizeof buf);
    if (k <= 0) {
      if (k < 0 && (errno == EINTR || errno == EAGAIN)) return true;
      dead = true;
      return false;
    }
    rbuf.append(buf, (size_t)k);
    return true;
  }
};

int DialTcp(const std::string& hostport, std::string* err) {
  auto colon = hostport.rfind(':');
  std::string host = hostport.substr(0, colon);
  int port = atoi(hostport.c_str() + colon + 1);
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  inet_pton(AF_INET, host.c_str(), &addr.sin_addr);
  for (int attempt = 0; attempt < 50; ++attempt) {
    if (connect(fd, (sockaddr*)&addr, sizeof addr) == 0) {
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
      return fd;
    }
    usleep(100 * 1000);
  }
  *err = "connect " + hostport + ": " + strerror(errno);
  close(fd);
  return -1;
}

// ---------------------------------------------------------------- user ABI
// Mirrors task_api.h's extern "C" exports.
typedef int (*InvokeFn)(const char* name, const char* args, size_t args_len,
                        char** out, size_t* out_len, char** err);
typedef int (*ActorNewFn)(const char* cls, const char* args, size_t args_len,
                          void** instance, char** err);
typedef int (*ActorCallFn)(void* instance, const char* method,
                           const char* args, size_t args_len, char** out,
                           size_t* out_len, char** err);
typedef void (*ActorDelFn)(void* instance);
typedef void (*FreeFn)(char* p);

struct UserLib {
  void* dl = nullptr;
  InvokeFn invoke = nullptr;
  ActorNewFn actor_new = nullptr;
  ActorCallFn actor_call = nullptr;
  ActorDelFn actor_del = nullptr;
  FreeFn free_buf = nullptr;
};

// --------------------------------------------------------------- the worker
class Worker {
 public:
  Worker(std::string nodelet, std::string controller, std::string store_path,
         std::string node_id, std::string worker_id_hex,
         std::string session_dir)
      : nodelet_addr_(std::move(nodelet)),
        controller_addr_(std::move(controller)),
        store_path_(std::move(store_path)),
        node_id_(std::move(node_id)),
        session_dir_(std::move(session_dir)) {
    for (size_t i = 0; i + 1 < worker_id_hex.size(); i += 2)
      worker_id_.push_back(
          (char)strtol(worker_id_hex.substr(i, 2).c_str(), nullptr, 16));
  }

  int Run() {
    std::string err;
    // store segment: same dlopened library the Python client builds
    std::string lib = store_path_;
    auto slash = lib.rfind('/');
    (void)slash;
    const char* libpath = getenv("RAY_TPU_STORE_LIB");
    store_ = OpenStore(libpath ? libpath : "libtpustore.so", store_path_,
                       &err);
    if (!store_.handle) {
      fprintf(stderr, "cpp_worker: %s\n", err.c_str());
      return 1;
    }
    if (!Listen(&err) || !Register(&err)) {
      fprintf(stderr, "cpp_worker: %s\n", err.c_str());
      return 1;
    }
    Loop();
    return 0;
  }

 private:
  bool Listen(std::string* err) {
    listen_fd_ = socket(AF_INET, SOCK_STREAM, 0);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    addr.sin_port = 0;
    if (bind(listen_fd_, (sockaddr*)&addr, sizeof addr) != 0 ||
        listen(listen_fd_, 64) != 0) {
      *err = std::string("bind/listen: ") + strerror(errno);
      return false;
    }
    socklen_t len = sizeof addr;
    getsockname(listen_fd_, (sockaddr*)&addr, &len);
    port_ = ntohs(addr.sin_port);
    return true;
  }

  bool Register(std::string* err) {
    int fd = DialTcp(nodelet_addr_, err);
    if (fd < 0) return false;
    nodelet_ = std::make_unique<Conn>();
    nodelet_->fd = fd;
    Val req = Val::MapOf({{"worker_id", Val::Bin(worker_id_)},
                          {"port", Val::Of((int64_t)port_)},
                          {"lang", Val::Str("cpp")}});
    Val reply = Call(nodelet_.get(), "register_worker", req);
    if (reply.has("error") && !reply.at("error").is_nil()) {
      *err = "register_worker: " + reply.at("error").as_str();
      return false;
    }
    if (reply.has("config")) {
      const Val& cfg = reply.at("config");
      if (cfg.has("max_direct_call_object_size"))
        inline_cap_ = (size_t)cfg.at("max_direct_call_object_size").as_int();
    }
    return true;
  }

  // Synchronous request on a bidirectional connection: requests arriving
  // while we wait are queued and dispatched after (the nodelet pushes
  // create_actor over the worker's own registration connection).
  Val Call(Conn* c, const std::string& method, const Val& data) {
    int64_t seq = ++c->next_seq;
    c->Send(seq, kRequest, method, data);
    while (!c->dead) {
      Val frame;
      while (!c->PopFrame(&frame)) {
        if (!c->Fill() || c->dead)
          return Val::MapOf({{"error", Val::Str("connection lost")}});
      }
      int kind = (int)frame.arr[1].as_int();
      if ((kind == kReply || kind == kError) &&
          frame.arr[0].as_int() == seq) {
        if (kind == kError)
          return Val::MapOf({{"error", frame.arr[3]}});
        return frame.arr[3];
      }
      if (kind == kRequest || kind == kNotify) {
        pending_.push_back({c, frame});
      }
      // stale replies to earlier (abandoned) calls: drop
    }
    return Val::MapOf({{"error", Val::Str("connection lost")}});
  }

  Val Controller() {
    // lazy controller connection (only actors need it)
    if (!controller_) {
      std::string err;
      int fd = DialTcp(controller_addr_, &err);
      if (fd >= 0) {
        controller_ = std::make_unique<Conn>();
        controller_->fd = fd;
      }
    }
    return Val();
  }

  void Loop() {
    while (!exiting_) {
      // deferred requests first (arrived during a synchronous Call)
      while (!pending_.empty()) {
        auto item = pending_.front();
        pending_.erase(pending_.begin());
        Val frame = item.second;
        Dispatch(item.first, frame);
      }
      // Frames can sit fully-buffered in a conn's rbuf after a blocking
      // Call() read more than its own reply (e.g. create_actor arriving
      // right behind the register_worker reply) — poll() will never
      // signal for them, so drain buffers before sleeping.
      bool drained_any = true;
      while (drained_any) {
        drained_any = false;
        Val frame;
        while (nodelet_->PopFrame(&frame)) {
          drained_any = true;
          Dispatch(nodelet_.get(), frame);
        }
        for (auto& c : driver_conns_)
          while (!c->dead && c->PopFrame(&frame)) {
            drained_any = true;
            Dispatch(c.get(), frame);
          }
        if (!pending_.empty()) break;  // outer loop handles these first
      }
      if (!pending_.empty()) continue;
      std::vector<pollfd> fds;
      std::vector<Conn*> polled;          // parallel to fds[2..]
      fds.push_back({listen_fd_, POLLIN, 0});
      fds.push_back({nodelet_->fd, POLLIN, 0});
      for (auto& c : driver_conns_)
        if (!c->dead) {
          fds.push_back({c->fd, POLLIN, 0});
          polled.push_back(c.get());
        }
      if (poll(fds.data(), fds.size(), 1000) <= 0) continue;
      if (fds[0].revents & POLLIN) Accept();  // new conns poll next round
      if (fds[1].revents & POLLIN) Pump(nodelet_.get());
      for (size_t k = 0; k < polled.size(); ++k)
        if (fds[2 + k].revents & POLLIN) Pump(polled[k]);
      driver_conns_.erase(
          std::remove_if(driver_conns_.begin(), driver_conns_.end(),
                         [](const std::unique_ptr<Conn>& c) {
                           return c->dead;
                         }),
          driver_conns_.end());
      if (nodelet_->dead) exiting_ = true;  // nodelet gone: die with it
    }
  }

  void Accept() {
    int fd = accept(listen_fd_, nullptr, nullptr);
    if (fd < 0) return;
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    auto c = std::make_unique<Conn>();
    c->fd = fd;
    driver_conns_.push_back(std::move(c));
  }

  void Pump(Conn* c) {
    if (!c->Fill()) return;
    Val frame;
    while (c->PopFrame(&frame)) Dispatch(c, frame);
  }

  void Dispatch(Conn* c, const Val& frame) {
    int64_t seq = frame.arr[0].as_int();
    int kind = (int)frame.arr[1].as_int();
    const std::string& method = frame.arr[2].as_str();
    const Val& data = frame.arr[3];
    if (debug_)
      fprintf(stderr, "cpp_worker: dispatch %s kind=%d seq=%ld\n",
              method.c_str(), kind, (long)seq);
    if (kind != kRequest && kind != kNotify) return;
    Val reply;
    try {
      reply = Route(method, data);
    } catch (const std::exception& e) {
      reply = ErrorReply(std::string("worker internal error: ") + e.what(),
                         method);
    }
    if (kind == kRequest) c->Send(seq, kReply, method, reply);
  }

  Val Route(const std::string& method, const Val& data) {
    Val reply;
    if (method == "ping") {
      reply = Val::Str("pong");
    } else if (method == "exit") {
      exiting_ = true;
      reply = Val::Of(true);
    } else if (method == "push_task") {
      reply = ExecuteTask(data.at("spec"), /*actor_method=*/false);
    } else if (method == "create_actor") {
      reply = CreateActor(data.at("spec"));
    } else if (method == "push_actor_task") {
      reply = ExecuteTask(data.at("spec"), /*actor_method=*/true);
    } else if (method == "cancel_task") {
      reply = Val::Of(false);  // single-threaded: nothing interruptible
    } else {
      reply = Val::MapOf({{"error", Val::Str("no handler " + method)}});
    }
    return reply;
  }

  // ------------------------------------------------------------- user libs
  UserLib* LoadLib(const std::string& path, std::string* err) {
    auto it = libs_.find(path);
    if (it != libs_.end()) return &it->second;
    UserLib lib;
    lib.dl = dlopen(path.c_str(), RTLD_NOW | RTLD_LOCAL);
    if (!lib.dl) {
      *err = std::string("dlopen ") + path + ": " + dlerror();
      return nullptr;
    }
    lib.invoke = (InvokeFn)dlsym(lib.dl, "ray_tpu_cpp_invoke");
    lib.actor_new = (ActorNewFn)dlsym(lib.dl, "ray_tpu_cpp_actor_new");
    lib.actor_call = (ActorCallFn)dlsym(lib.dl, "ray_tpu_cpp_actor_call");
    lib.actor_del = (ActorDelFn)dlsym(lib.dl, "ray_tpu_cpp_actor_destroy");
    lib.free_buf = (FreeFn)dlsym(lib.dl, "ray_tpu_cpp_free");
    if (!lib.invoke) {
      *err = path + " does not export ray_tpu_cpp_invoke (build it "
             "against ray_tpu/cpp/task_api.h)";
      return nullptr;
    }
    return &libs_.emplace(path, lib).first->second;
  }

  // -------------------------------------------------------- args / returns
  static Val ErrorReply(const std::string& tb, const std::string& fname) {
    return Val::MapOf({{"error", Val::MapOf({{"traceback", Val::Str(tb)},
                                             {"pickled", Val::Nil()},
                                             {"fname", Val::Str(fname)}})}});
  }

  bool ResolveArgs(const Val& spec, std::string* packed_args,
                   std::string* err) {
    // Build one msgpack array of the positional args (xlang values).
    std::vector<Val> out;
    for (const auto& a : spec.at("args").arr) {
      int kind = (int)a.arr[0].as_int();
      const std::string& payload = a.arr[1].as_str();
      if (kind == kArgValue) {
        if (payload.size() < 4 || memcmp(payload.data(), kXMagic, 4) != 0) {
          *err = "argument is a Python-pickled object; only RTX1 xlang "
                 "values (nil/bool/int/float/str/bytes/list/dict) cross "
                 "into C++ tasks";
          return false;
        }
        out.push_back(Unpack(payload.substr(4)));
      } else {
        std::string blob;
        if (!FetchObject(payload, &blob, err)) return false;
        if (blob.size() < 4 || memcmp(blob.data(), kXMagic, 4) != 0) {
          *err = "object " + Hex(payload) + " is a Python-pickled value; "
                 "it does not cross the xlang boundary";
          return false;
        }
        out.push_back(Unpack(blob.substr(4)));
      }
    }
    *packed_args = Pack(Val::Arr(std::move(out)));
    return true;
  }

  bool FetchObject(const std::string& id, std::string* blob,
                   std::string* err) {
    uint64_t off = 0, size = 0;
    int rc = store_.get(store_.handle, (const uint8_t*)id.data(), 0, &off,
                        &size);
    if (rc != 0) {
      // ask the nodelet to pull it to this node (remote or evicted)
      Val r = Call(nodelet_.get(), "pull",
                   Val::MapOf({{"object_id", Val::Bin(id)}}));
      bool ok = r.has("ok") && r.at("ok").type == Val::Type::Bool &&
                r.at("ok").b;
      if (!ok) {
        *err = "object " + Hex(id) + " could not be pulled";
        return false;
      }
      rc = store_.get(store_.handle, (const uint8_t*)id.data(), 5000, &off,
                      &size);
      if (rc != 0) {
        *err = "object " + Hex(id) + " pull raced eviction";
        return false;
      }
    }
    blob->assign((const char*)(store_.base + off), size);
    store_.release(store_.handle, (const uint8_t*)id.data());
    return true;
  }

  static std::string Hex(const std::string& b) {
    static const char* d = "0123456789abcdef";
    std::string s;
    for (unsigned char ch : b) {
      s.push_back(d[ch >> 4]);
      s.push_back(d[ch & 15]);
    }
    return s;
  }

  Val StoreReturns(const Val& spec, const std::string& result_payload) {
    // result_payload: msgpack of the return VALUE (single return).
    std::string blob(kXMagic, 4);
    blob += result_payload;
    if (blob.size() <= inline_cap_) {
      return Val::MapOf({{"returns", Val::Arr({Val::MapOf(
                             {{"inline", Val::Bin(blob)},
                              {"contained", Val::Of(false)}})})}});
    }
    // large return: straight into the shared-memory store.  Return ids
    // are derived, not shipped: task_id + LE uint32 index
    // (core/ids.py ObjectID.for_task_return)
    std::string oid = spec.at("tid").as_str();
    oid.append(4, '\0');  // index 0, little-endian
    int64_t off = store_.create(store_.handle, (const uint8_t*)oid.data(),
                                blob.size());
    if (off >= 0) {
      memcpy(store_.base + off, blob.data(), blob.size());
      store_.seal(store_.handle, (const uint8_t*)oid.data());
      Call(nodelet_.get(), "put_location",
           Val::MapOf({{"object_id", Val::Bin(oid)},
                       {"size", Val::Of((int64_t)blob.size())}}));
      return Val::MapOf({{"returns", Val::Arr({Val::MapOf(
                             {{"plasma", Val::Of((int64_t)blob.size())},
                              {"contained", Val::Of(false)}})})}});
    }
    return ErrorReply("store full for " + std::to_string(blob.size()) +
                          "-byte return",
                      spec.at("fname").as_str());
  }

  // --------------------------------------------------------- task execution
  // fname convention: "path/to/libuser.so:function" (tasks) or
  // "path/to/libuser.so:Class" (actor creation); actor methods are bare
  // method names (the library is remembered from creation).
  Val ExecuteTask(const Val& spec, bool actor_method) {
    std::string fname = spec.at("fname").as_str();
    std::string err;
    std::string packed_args;
    if (!ResolveArgs(spec, &packed_args, &err))
      return ErrorReply(err, fname);

    char* out = nullptr;
    size_t out_len = 0;
    char* uerr = nullptr;
    UserLib* lib = nullptr;
    int rc;
    if (actor_method) {
      if (!actor_instance_)
        return ErrorReply("actor instance not created", fname);
      lib = actor_lib_;
      rc = lib->actor_call(actor_instance_, fname.c_str(),
                           packed_args.data(), packed_args.size(), &out,
                           &out_len, &uerr);
    } else {
      auto colon = fname.rfind(':');
      if (colon == std::string::npos)
        return ErrorReply("cpp task fname must be 'lib.so:function', got " +
                              fname,
                          fname);
      lib = LoadLib(fname.substr(0, colon), &err);
      if (!lib) return ErrorReply(err, fname);
      std::string sym = fname.substr(colon + 1);
      rc = lib->invoke(sym.c_str(), packed_args.data(), packed_args.size(),
                       &out, &out_len, &uerr);
    }
    if (rc != 0) {
      std::string tb = uerr ? uerr : "cpp task failed";
      if (uerr && lib->free_buf) lib->free_buf(uerr);
      return ErrorReply(tb, fname);
    }
    std::string payload(out, out_len);
    if (out && lib->free_buf) lib->free_buf(out);
    return StoreReturns(spec, payload);
  }

  Val CreateActor(const Val& spec) {
    std::string fname = spec.at("fname").as_str();
    auto colon = fname.rfind(':');
    if (colon == std::string::npos)
      return Val::MapOf({{"ok", Val::Of(false)},
                         {"error", Val::Str("cpp actor fname must be "
                                            "'lib.so:Class'")}});
    std::string err;
    UserLib* lib = LoadLib(fname.substr(0, colon), &err);
    if (!lib) return Val::MapOf({{"ok", Val::Of(false)},
                                 {"error", Val::Str(err)}});
    if (!lib->actor_new)
      return Val::MapOf({{"ok", Val::Of(false)},
                         {"error", Val::Str("library exports no actor "
                                            "ABI")}});
    std::string packed_args;
    if (!ResolveArgs(spec, &packed_args, &err))
      return Val::MapOf({{"ok", Val::Of(false)}, {"error", Val::Str(err)}});
    char* uerr = nullptr;
    void* inst = nullptr;
    int rc = lib->actor_new(fname.substr(colon + 1).c_str(),
                            packed_args.data(), packed_args.size(), &inst,
                            &uerr);
    if (rc != 0) {
      std::string e = uerr ? uerr : "actor construction failed";
      if (uerr && lib->free_buf) lib->free_buf(uerr);
      return Val::MapOf({{"ok", Val::Of(false)}, {"error", Val::Str(e)}});
    }
    actor_instance_ = inst;
    actor_lib_ = lib;
    actor_id_ = spec.at("actor_new").as_str();
    // announce liveness to the controller (actor FSM → ALIVE), exactly
    // like worker_runtime._h_create_actor
    std::string cerr;
    int fd = DialTcp(controller_addr_, &cerr);
    if (fd >= 0) {
      controller_ = std::make_unique<Conn>();
      controller_->fd = fd;
      Call(controller_.get(), "actor_alive",
           Val::MapOf({{"actor_id", Val::Bin(actor_id_)},
                       {"address",
                        Val::Str("127.0.0.1:" + std::to_string(port_))},
                       {"worker_id", Val::Bin(worker_id_)},
                       {"node_id", Val::Str(node_id_)}}));
    }
    return Val::MapOf({{"ok", Val::Of(true)}});
  }

  std::string nodelet_addr_, controller_addr_, store_path_, node_id_,
      session_dir_;
  std::string worker_id_;
  int listen_fd_ = -1;
  uint16_t port_ = 0;
  size_t inline_cap_ = 100 * 1024;
  StoreApi store_;
  std::unique_ptr<Conn> nodelet_, controller_;
  std::vector<std::unique_ptr<Conn>> driver_conns_;
  std::vector<std::pair<Conn*, Val>> pending_;
  std::map<std::string, UserLib> libs_;
  void* actor_instance_ = nullptr;
  UserLib* actor_lib_ = nullptr;
  std::string actor_id_;
  bool exiting_ = false;
  bool debug_ = getenv("RAY_TPU_CPP_WORKER_DEBUG") != nullptr;
};

}  // namespace

int main(int argc, char** argv) {
  // build.py probes binary health (a stale prebuilt linked against a
  // newer glibc fails in the loader, before main) with --selftest
  if (argc > 1 && std::string(argv[1]) == "--selftest") {
    std::printf("ok\n");
    return 0;
  }
  std::map<std::string, std::string> args;
  for (int i = 1; i + 1 < argc; i += 2) args[argv[i]] = argv[i + 1];
  Worker w(args["--nodelet"], args["--controller"], args["--store"],
           args["--node-id"], args["--worker-id"], args["--session-dir"]);
  return w.Run();
}
