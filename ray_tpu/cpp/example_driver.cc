// End-to-end C++ driver exercising the full client surface against a
// live cluster (role of the reference's cpp/src example/test drivers).
// Prints one CHECK line per capability; exits non-zero on any failure.
//
// Usage: example_driver <host> <port> <callee_module>
//   callee_module exports: square(x), add(a, b), Counter(start) with
//   incr(n)/total() — see tests/test_cpp_api.py which generates it.

#include <cstdlib>
#include <iostream>
#include <string>

#include "ray_tpu_client.h"

using ray_tpu::Client;
using ray_tpu::Val;

static int failures = 0;

static void Check(bool ok, const std::string& what) {
  std::cout << (ok ? "PASS " : "FAIL ") << what << std::endl;
  if (!ok) ++failures;
}

int main(int argc, char** argv) {
  if (argc < 4) {
    std::cerr << "usage: example_driver <host> <port> <callee_module>"
              << std::endl;
    return 2;
  }
  std::string host = argv[1];
  int port = std::atoi(argv[2]);
  std::string mod = argv[3];

  Client c;
  c.Connect(host, port);
  Check(c.connected() && !c.job_id().empty(), "connect+hello");

  // put/get raw bytes
  auto id = c.Put("hello from c++");
  auto got = c.Get(id, 30.0);
  Check(got.ok && got.value.as_str() == "hello from c++", "put/get bytes");

  // task call: square(7) -> 49
  auto ids = c.Call(mod + ":square", {Val::Of(7)});
  auto sq = c.Get(ids[0], 60.0);
  Check(sq.ok && sq.value.as_int() == 49, "xlang task call");

  // multi-arg + float
  auto ids2 = c.Call(mod + ":add", {Val::Of(2.5), Val::Of(4.0)});
  auto sum = c.Get(ids2[0], 60.0);
  Check(sum.ok && sum.value.as_float() == 6.5, "xlang float args");

  // object ref as plain value round trip through wait
  auto pending = c.Call(mod + ":square", {Val::Of(3)});
  auto wr = c.Wait(pending, 1, 60.0);
  Check(wr.first.size() == 1, "wait ready");

  // actors
  auto actor = c.CreateActor(mod + ":Counter", {Val::Of(10)});
  c.Get(c.ActorCall(actor, "incr", {Val::Of(5)}), 60.0);
  c.Get(c.ActorCall(actor, "incr", {Val::Of(7)}), 60.0);
  auto total = c.Get(c.ActorCall(actor, "total", {}), 60.0);
  Check(total.ok && total.value.as_int() == 22, "xlang actor state");

  // structured values across the boundary
  auto ids3 = c.Call(mod + ":describe",
                     {Val::Arr({Val::Of(1), Val::Str("two")})});
  auto desc = c.Get(ids3[0], 60.0);
  Check(desc.ok && desc.value.at("len").as_int() == 2 &&
            desc.value.at("first").as_int() == 1,
        "xlang dict/list boundary");

  c.KillActor(actor);
  bool dead = false;
  try {
    c.Get(c.ActorCall(actor, "total", {}), 15.0);
  } catch (const std::exception&) {
    dead = true;  // server forgets the killed actor's handle
  }
  Check(dead, "kill actor");

  // error surfaces, not hangs
  bool raised = false;
  try {
    c.Call("not_a_module_xyz:nope", {});
  } catch (const std::exception&) {
    raised = true;
  }
  Check(raised, "bad target raises");

  c.Release({id});
  c.Close();
  Check(!c.connected(), "close");

  std::cout << (failures == 0 ? "CPP_DRIVER_OK" : "CPP_DRIVER_FAILED")
            << std::endl;
  return failures == 0 ? 0 : 1;
}
