// Implementation of the C++ driver client (see ray_tpu_client.h).
//
// Wire protocol (must match ray_tpu/core/rpc.py): every frame is a
// 4-byte little-endian length followed by msgpack [seq, kind, method,
// data], kind in {REQUEST=0, REPLY=1, ERROR=2, NOTIFY=3}.  This client
// issues REQUESTs and matches REPLY/ERROR by seq; NOTIFY frames (pubsub
// pushes) are skipped.

#include "ray_tpu_client.h"

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>
#include <cstring>
#include <stdexcept>

namespace ray_tpu {

namespace {
constexpr int kRequest = 0;
constexpr int kReply = 1;
constexpr int kError = 2;
constexpr int kNotify = 3;

void WriteAll(int fd, const char* data, size_t n) {
  while (n > 0) {
    // MSG_NOSIGNAL: a server-side disconnect must surface as the
    // exception below, not deliver SIGPIPE and kill the host process.
    ssize_t w = ::send(fd, data, n, MSG_NOSIGNAL);
    if (w < 0 && errno == EINTR) continue;  // signal during send: retry
    if (w <= 0) throw std::runtime_error("ray_tpu: connection write failed");
    data += w;
    n -= size_t(w);
  }
}

void ReadAll(int fd, char* data, size_t n) {
  while (n > 0) {
    ssize_t r = ::read(fd, data, n);
    if (r < 0 && errno == EINTR) continue;  // signal during read: retry
    if (r <= 0) throw std::runtime_error("ray_tpu: connection closed");
    data += r;
    n -= size_t(r);
  }
}

Val IdArray(const std::vector<std::string>& ids) {
  std::vector<Val> out;
  out.reserve(ids.size());
  for (const auto& id : ids) out.push_back(Val::Bin(id));
  return Val::Arr(std::move(out));
}
}  // namespace

Client::~Client() { Close(); }

void Client::Connect(const std::string& host, int port) {
  struct addrinfo hints;
  std::memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  std::string port_s = std::to_string(port);
  if (getaddrinfo(host.c_str(), port_s.c_str(), &hints, &res) != 0 || !res)
    throw std::runtime_error("ray_tpu: cannot resolve " + host);
  fd_ = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
  if (fd_ < 0 || ::connect(fd_, res->ai_addr, res->ai_addrlen) != 0) {
    freeaddrinfo(res);
    if (fd_ >= 0) ::close(fd_);
    fd_ = -1;
    throw std::runtime_error("ray_tpu: cannot connect to " + host + ":" +
                             port_s);
  }
  freeaddrinfo(res);
  int one = 1;
  setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  Val hello = Request("client_hello", Val::MapOf({}));
  job_id_ = hello.at("job_id").as_str();
}

void Client::Close() {
  if (fd_ >= 0) {
    try {
      Request("client_bye", Val::MapOf({}));
    } catch (...) {
    }
    ::close(fd_);
    fd_ = -1;
  }
}

void Client::SendFrame(const std::string& payload) {
  uint32_t len = uint32_t(payload.size());
  char hdr[4] = {char(len & 0xff), char((len >> 8) & 0xff),
                 char((len >> 16) & 0xff), char((len >> 24) & 0xff)};
  WriteAll(fd_, hdr, 4);
  WriteAll(fd_, payload.data(), payload.size());
}

std::string Client::RecvFrame() {
  char hdr[4];
  ReadAll(fd_, hdr, 4);
  uint32_t len = uint32_t(uint8_t(hdr[0])) | (uint32_t(uint8_t(hdr[1])) << 8) |
                 (uint32_t(uint8_t(hdr[2])) << 16) |
                 (uint32_t(uint8_t(hdr[3])) << 24);
  std::string payload(len, '\0');
  ReadAll(fd_, payload.data(), len);
  return payload;
}

Val Client::Request(const std::string& method, Val data) {
  if (fd_ < 0) throw std::runtime_error("ray_tpu: not connected");
  int64_t seq = ++seq_;
  Val frame = Val::Arr({Val::Of(seq), Val::Of(int64_t(kRequest)),
                        Val::Str(method), std::move(data)});
  SendFrame(msgpack_lite::Pack(frame));
  for (;;) {
    Val reply = msgpack_lite::Unpack(RecvFrame());
    if (reply.arr.size() != 4) throw std::runtime_error("ray_tpu: bad frame");
    int64_t kind = reply.arr[1].as_int();
    if (kind == kNotify || kind == kRequest) continue;  // pubsub push: skip
    if (reply.arr[0].as_int() != seq) continue;         // stale reply
    if (kind == kError)
      throw std::runtime_error("ray_tpu: remote error: " +
                               reply.arr[3].as_str());
    return reply.arr[3];
  }
}

ObjectId Client::Put(const std::string& bytes) {
  Val r = Request("client_xlang_put", Val::MapOf({{"blob", Val::Bin(bytes)}}));
  return r.at("object_id").as_str();
}

std::vector<GetResult> Client::Get(const std::vector<ObjectId>& ids,
                                   std::optional<double> timeout_s) {
  std::map<std::string, Val> req{{"object_ids", IdArray(ids)}};
  if (timeout_s) req["timeout"] = Val::Of(*timeout_s);
  Val r = Request("client_xlang_get", Val::MapOf(std::move(req)));
  std::vector<GetResult> out;
  for (const auto& entry : r.at("results").arr) {
    GetResult g;
    if (entry.has("timeout") && entry.at("timeout").b) {
      g.timeout = true;
    } else if (entry.has("error")) {
      g.error = entry.at("error").as_str();
    } else {
      g.ok = true;
      g.value = entry.at("value");
    }
    out.push_back(std::move(g));
  }
  return out;
}

GetResult Client::Get(const ObjectId& id, std::optional<double> timeout_s) {
  return Get(std::vector<ObjectId>{id}, timeout_s)[0];
}

std::pair<std::vector<ObjectId>, std::vector<ObjectId>> Client::Wait(
    const std::vector<ObjectId>& ids, int num_returns,
    std::optional<double> timeout_s) {
  std::map<std::string, Val> req{{"object_ids", IdArray(ids)},
                                 {"num_returns", Val::Of(num_returns)}};
  if (timeout_s) req["timeout"] = Val::Of(*timeout_s);
  Val r = Request("client_wait", Val::MapOf(std::move(req)));
  std::pair<std::vector<ObjectId>, std::vector<ObjectId>> out;
  for (const auto& v : r.at("ready").arr) out.first.push_back(v.as_str());
  for (const auto& v : r.at("not_ready").arr) out.second.push_back(v.as_str());
  return out;
}

std::vector<ObjectId> Client::Call(const std::string& function,
                                   const std::vector<Val>& args,
                                   int num_returns) {
  Val r = Request("client_xlang_call",
                  Val::MapOf({{"function", Val::Str(function)},
                              {"args", Val::Arr(args)},
                              {"num_returns", Val::Of(num_returns)}}));
  if (r.has("error"))
    throw std::runtime_error("ray_tpu: call failed: " + r.at("error").as_str());
  std::vector<ObjectId> out;
  for (const auto& v : r.at("object_ids").arr) out.push_back(v.as_str());
  return out;
}

ActorId Client::CreateActor(const std::string& actor_class,
                            const std::vector<Val>& args) {
  Val r = Request("client_xlang_create_actor",
                  Val::MapOf({{"actor_class", Val::Str(actor_class)},
                              {"args", Val::Arr(args)}}));
  if (r.has("error"))
    throw std::runtime_error("ray_tpu: actor creation failed: " +
                             r.at("error").as_str());
  return r.at("actor_id").as_str();
}

ObjectId Client::ActorCall(const ActorId& actor, const std::string& method,
                           const std::vector<Val>& args) {
  Val r = Request("client_xlang_actor_call",
                  Val::MapOf({{"actor_id", Val::Bin(actor)},
                              {"method", Val::Str(method)},
                              {"args", Val::Arr(args)}}));
  if (r.has("error"))
    throw std::runtime_error("ray_tpu: actor call failed: " +
                             r.at("error").as_str());
  return r.at("object_ids").arr[0].as_str();
}

void Client::KillActor(const ActorId& actor) {
  Val r = Request("client_xlang_kill_actor",
                  Val::MapOf({{"actor_id", Val::Bin(actor)}}));
  if (r.has("error"))
    throw std::runtime_error("ray_tpu: kill failed: " + r.at("error").as_str());
}

void Client::Release(const std::vector<ObjectId>& ids) {
  Request("client_ref_dec", Val::MapOf({{"object_ids", IdArray(ids)}}));
}

}  // namespace ray_tpu
