// C++ driver API for the ray_tpu cluster.
//
// Role of the reference's C++ worker API (cpp/include/ray/api/ in
// /root/reference: ray::Init, ray::Put/Get, ray::Task(...).Remote(),
// actor handles, xlang calls) — redesigned for this framework's remote-
// driver endpoint: the client speaks the length-framed msgpack protocol
// to a ClientServer (ray_tpu/client/server.py) and crosses the language
// boundary with msgpack-typed values, invoking Python callees by
// "module:qualname" exactly like the reference's cross-language calls.
//
// Synchronous, single-connection, no external dependencies.
//
//   ray_tpu::Client c;
//   c.Connect("127.0.0.1", port);
//   auto id  = c.Put("raw bytes");
//   auto ids = c.Call("my_pkg.funcs:square", {ray_tpu::Val::Of(7)});
//   auto v   = c.Get(ids[0], /*timeout_s=*/30.0);   // v.as_int() == 49
//   auto actor = c.CreateActor("my_pkg.funcs:Counter", {});
//   auto r = c.ActorCall(actor, "incr", {ray_tpu::Val::Of(5)});
#pragma once

#include <optional>
#include <string>
#include <vector>

#include "msgpack_lite.h"

namespace ray_tpu {

using Val = msgpack_lite::Value;

using ObjectId = std::string;   // opaque binary object id
using ActorId = std::string;    // opaque binary actor id

struct GetResult {
  bool ok = false;
  bool timeout = false;
  std::string error;
  Val value;
};

class Client {
 public:
  Client() = default;
  ~Client();
  Client(const Client&) = delete;
  Client& operator=(const Client&) = delete;

  // Dial a ClientServer endpoint; performs the hello handshake.
  void Connect(const std::string& host, int port);
  void Close();
  bool connected() const { return fd_ >= 0; }

  const std::string& job_id() const { return job_id_; }

  // Store raw bytes as an object (arrives Python-side as `bytes`).
  ObjectId Put(const std::string& bytes);

  // Fetch one object across the msgpack boundary.
  GetResult Get(const ObjectId& id, std::optional<double> timeout_s = {});
  std::vector<GetResult> Get(const std::vector<ObjectId>& ids,
                             std::optional<double> timeout_s = {});

  // ray.wait: first `num_returns` ready ids (ready, not_ready).
  std::pair<std::vector<ObjectId>, std::vector<ObjectId>> Wait(
      const std::vector<ObjectId>& ids, int num_returns,
      std::optional<double> timeout_s = {});

  // Invoke a Python function by "module:qualname"; returns object ids.
  std::vector<ObjectId> Call(const std::string& function,
                             const std::vector<Val>& args,
                             int num_returns = 1);

  // Create a Python actor by "module:QualName"; call its methods.
  ActorId CreateActor(const std::string& actor_class,
                      const std::vector<Val>& args);
  ObjectId ActorCall(const ActorId& actor, const std::string& method,
                     const std::vector<Val>& args);
  void KillActor(const ActorId& actor);

  // Drop the server-side mirror refs for ids this client is done with.
  void Release(const std::vector<ObjectId>& ids);

 private:
  Val Request(const std::string& method, Val data);
  void SendFrame(const std::string& payload);
  std::string RecvFrame();

  int fd_ = -1;
  int64_t seq_ = 0;
  std::string job_id_;
};

}  // namespace ray_tpu
