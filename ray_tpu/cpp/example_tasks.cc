// Example C++ task library (also the test fixture for
// tests/test_cpp_worker.py) — the counterpart of the reference's
// cpp/example/example.cc RAY_REMOTE demo, executed by the native worker.
//
// Build: g++ -O2 -shared -fPIC -std=c++17 example_tasks.cc -o libexample.so
#include "task_api.h"

using ray_tpu::msgpack_lite::Value;

static Value Add(const std::vector<Value>& args) {
  return Value::Of(args[0].as_int() + args[1].as_int());
}
RAY_TPU_REMOTE(Add);

static Value Concat(const std::vector<Value>& args) {
  return Value::Str(args[0].as_str() + args[1].as_str());
}
RAY_TPU_REMOTE(Concat);

// Sums a list argument — exercises nested xlang values.
static Value SumList(const std::vector<Value>& args) {
  int64_t total = 0;
  for (const auto& v : args[0].arr) total += v.as_int();
  return Value::Of(total);
}
RAY_TPU_REMOTE(SumList);

// Returns a large bytes payload — exercises the shared-memory return
// path (result > max_direct_call_object_size goes to the store).
static Value BigBlob(const std::vector<Value>& args) {
  return Value::Bin(std::string((size_t)args[0].as_int(), 'x'));
}
RAY_TPU_REMOTE(BigBlob);

static Value Fail(const std::vector<Value>&) {
  throw std::runtime_error("deliberate C++ task failure");
}
RAY_TPU_REMOTE(Fail);

struct Counter : ray_tpu::CppActor {
  int64_t n;
  explicit Counter(const std::vector<Value>& args)
      : n(args.empty() ? 0 : args[0].as_int()) {}
  Value Call(const std::string& m, const std::vector<Value>& a) override {
    if (m == "add") {
      n += a[0].as_int();
      return Value::Of(n);
    }
    if (m == "get") return Value::Of(n);
    throw std::runtime_error("Counter has no method '" + m + "'");
  }
};
RAY_TPU_ACTOR(Counter);
