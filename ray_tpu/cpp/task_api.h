// User-facing C++ task/actor API: build your functions into a shared
// library the native worker executes.
//
// The reference's C++ worker API registers remote functions with
// RAY_REMOTE and executes them inside C++ workers
// (/root/reference/cpp/include/ray/api.h, RAY_REMOTE in
// cpp/include/ray/api/function_manager.h); this header is that surface
// for the TPU-native runtime.  Usage:
//
//   #include "task_api.h"
//   using ray_tpu::msgpack_lite::Value;
//   static Value Add(const std::vector<Value>& args) {
//     return Value::Of(args[0].as_int() + args[1].as_int());
//   }
//   RAY_TPU_REMOTE(Add);
//
//   struct Counter : ray_tpu::CppActor {
//     int64_t n = 0;
//     Value Call(const std::string& m,
//                const std::vector<Value>& a) override {
//       if (m == "add") { n += a[0].as_int(); return Value::Of(n); }
//       if (m == "get") return Value::Of(n);
//       throw std::runtime_error("no method " + m);
//     }
//   };
//   RAY_TPU_ACTOR(Counter);
//
// Compile: g++ -O2 -shared -fPIC -std=c++17 mylib.cc -o libmy.so
// Invoke from Python:
//   f = ray_tpu.cpp_function("/path/libmy.so", "Add")
//   ray_tpu.get(f.remote(2, 3))                      # -> 5
//   c = ray_tpu.cpp_actor("/path/libmy.so", "Counter").remote()
//   ray_tpu.get(c.task("add", 7))                    # -> 7
//
// Values cross the boundary as msgpack (RTX1 xlang format): nil, bool,
// int, float, str, bytes, list, dict — the same restriction the
// reference places on cross-language calls.
#pragma once

#include <cstring>
#include <functional>
#include <map>
#include <stdexcept>
#include <string>
#include <vector>

#include "msgpack_lite.h"

namespace ray_tpu {

using TaskFn = std::function<msgpack_lite::Value(
    const std::vector<msgpack_lite::Value>&)>;

struct CppActor {
  virtual msgpack_lite::Value Call(
      const std::string& method,
      const std::vector<msgpack_lite::Value>& args) = 0;
  virtual ~CppActor() = default;
};

using ActorFactory = std::function<CppActor*(
    const std::vector<msgpack_lite::Value>&)>;

inline std::map<std::string, TaskFn>& TaskRegistry() {
  static std::map<std::string, TaskFn> r;
  return r;
}

inline std::map<std::string, ActorFactory>& ActorRegistry() {
  static std::map<std::string, ActorFactory> r;
  return r;
}

struct TaskRegistrar {
  TaskRegistrar(const char* name, TaskFn fn) {
    TaskRegistry()[name] = std::move(fn);
  }
};

struct ActorRegistrar {
  ActorRegistrar(const char* name, ActorFactory f) {
    ActorRegistry()[name] = std::move(f);
  }
};

}  // namespace ray_tpu

#define RAY_TPU_REMOTE(fn)                                              \
  static ::ray_tpu::TaskRegistrar _ray_tpu_reg_##fn(#fn, fn)

#define RAY_TPU_ACTOR(cls)                                              \
  static ::ray_tpu::ActorRegistrar _ray_tpu_actor_##cls(                \
      #cls, [](const std::vector<::ray_tpu::msgpack_lite::Value>& a)    \
                -> ::ray_tpu::CppActor* { return new cls(a); })

// Variant for actors whose constructor ignores creation args.
#define RAY_TPU_ACTOR_NOARGS(cls)                                       \
  static ::ray_tpu::ActorRegistrar _ray_tpu_actor_##cls(                \
      #cls, [](const std::vector<::ray_tpu::msgpack_lite::Value>&)      \
                -> ::ray_tpu::CppActor* { return new cls(); })

// ----------------------------------------------------------------- worker ABI
// Fixed extern "C" surface the native worker dlopens.  Implemented once
// here (header-only): every user library exports the same symbols.
// ``inline`` keeps multi-TU inclusion ODR-clean (weak linkage);
// ``used`` + default visibility force the unreferenced definitions into
// the .so's dynamic symbol table for dlsym.
#define RAY_TPU_ABI \
  inline __attribute__((used, visibility("default")))

extern "C" {

RAY_TPU_ABI char* _ray_tpu_strdup(const std::string& s) {
  char* p = (char*)malloc(s.size() + 1);
  memcpy(p, s.data(), s.size() + 1);
  return p;
}

RAY_TPU_ABI int ray_tpu_cpp_invoke(const char* name, const char* args,
                              size_t args_len, char** out, size_t* out_len,
                              char** err) {
  try {
    auto& reg = ::ray_tpu::TaskRegistry();
    auto it = reg.find(name);
    if (it == reg.end())
      throw std::runtime_error(std::string("no registered task '") + name +
                               "' (RAY_TPU_REMOTE it)");
    auto arr =
        ::ray_tpu::msgpack_lite::Unpack(std::string(args, args_len)).arr;
    auto result = it->second(arr);
    std::string packed = ::ray_tpu::msgpack_lite::Pack(result);
    *out_len = packed.size();
    *out = (char*)malloc(packed.size());
    memcpy(*out, packed.data(), packed.size());
    return 0;
  } catch (const std::exception& e) {
    *err = _ray_tpu_strdup(e.what());
    return 1;
  }
}

RAY_TPU_ABI int ray_tpu_cpp_actor_new(const char* cls, const char* args,
                                 size_t args_len, void** instance,
                                 char** err) {
  try {
    auto& reg = ::ray_tpu::ActorRegistry();
    auto it = reg.find(cls);
    if (it == reg.end())
      throw std::runtime_error(std::string("no registered actor '") + cls +
                               "' (RAY_TPU_ACTOR it)");
    auto arr =
        ::ray_tpu::msgpack_lite::Unpack(std::string(args, args_len)).arr;
    *instance = it->second(arr);
    return 0;
  } catch (const std::exception& e) {
    *err = _ray_tpu_strdup(e.what());
    return 1;
  }
}

RAY_TPU_ABI int ray_tpu_cpp_actor_call(void* instance, const char* method,
                                  const char* args, size_t args_len,
                                  char** out, size_t* out_len, char** err) {
  try {
    auto arr =
        ::ray_tpu::msgpack_lite::Unpack(std::string(args, args_len)).arr;
    auto result =
        ((::ray_tpu::CppActor*)instance)->Call(method, arr);
    std::string packed = ::ray_tpu::msgpack_lite::Pack(result);
    *out_len = packed.size();
    *out = (char*)malloc(packed.size());
    memcpy(*out, packed.data(), packed.size());
    return 0;
  } catch (const std::exception& e) {
    *err = _ray_tpu_strdup(e.what());
    return 1;
  }
}

RAY_TPU_ABI void ray_tpu_cpp_actor_destroy(void* instance) {
  delete (::ray_tpu::CppActor*)instance;
}

RAY_TPU_ABI void ray_tpu_cpp_free(char* p) { free(p); }

}  // extern "C"
