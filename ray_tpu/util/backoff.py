"""Capped exponential backoff with full jitter.

One retry-delay policy for every reconnect/retry loop in the runtime
(reference: the AWS architecture-blog "exponential backoff and jitter"
full-jitter variant, which the reference's gcs_rpc_client reconnects and
Serve router approximate).  Full jitter — ``uniform(0, min(cap, base *
factor**attempt))`` — decorrelates a fleet of clients retrying against
the same restarted server: a fixed delay (the old 20 ms in
``rpc.connect``) wakes every nodelet and driver on the same tick and
thundering-herds the controller the moment it comes back.
"""

from __future__ import annotations

import random
import time
from typing import Optional


class ExponentialBackoff:
    """Stateful per-loop backoff: each ``next_delay()`` call advances the
    attempt counter and samples a full-jitter delay.

    The deterministic *envelope* (``envelope(n)``) grows monotonically
    ``base * factor**n`` up to ``cap``; the sampled delay is uniform in
    ``[0, envelope)``.  Pass ``rng`` for reproducible schedules (the
    chaos suite does)."""

    def __init__(self, base: float = 0.02, cap: float = 2.0,
                 factor: float = 2.0,
                 rng: Optional[random.Random] = None):
        if base <= 0:
            base = 1e-3
        self.base = base
        self.cap = max(cap, base)
        self.factor = max(factor, 1.0)
        self.attempt = 0
        self._rng = rng or random

    def envelope(self, attempt: Optional[int] = None) -> float:
        """Upper bound of the delay for ``attempt`` (default: the next
        one).  Monotone non-decreasing in ``attempt``, capped."""
        n = self.attempt if attempt is None else attempt
        # factor**n overflows for huge n; cap the exponent search instead
        env = self.base
        for _ in range(min(n, 64)):
            env *= self.factor
            if env >= self.cap:
                return self.cap
        return min(env, self.cap)

    def next_delay(self) -> float:
        """Sample the next full-jitter delay and advance the attempt."""
        env = self.envelope()
        self.attempt += 1
        return self._rng.uniform(0.0, env)

    def reset(self) -> None:
        self.attempt = 0

    def sleep(self) -> float:
        """Blocking convenience for sync retry loops; returns the delay
        actually slept."""
        d = self.next_delay()
        if d > 0:
            time.sleep(d)
        return d
