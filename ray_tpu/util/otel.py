"""OpenTelemetry span injection for tasks and actor calls.

Capability mirror of the reference's tracing helper
(`python/ray/util/tracing/tracing_helper.py:87` — wrap task submission
and execution in spans, propagate the W3C trace context inside the task
spec).  Only `opentelemetry-api` is required: with no SDK/provider
registered every span is the API's no-op span (zero overhead, the
reference behaves the same).  For environments without the SDK this
module also ships a minimal in-memory provider (`SpanRecorder`)
implementing the API surface, so tests and local debugging can observe
spans without extra packages.

Enable with ``ray_tpu.util.otel.enable_tracing()`` (or
``RAY_TPU_OTEL=1``) in the driver: the driver records a submit span per
task and ships its W3C context in the task spec; a worker opens the
matching execution span whenever a spec carries one — the context's
presence is the cross-process enablement signal, like the reference's
``--tracing-startup-hook`` wiring in tracing_helper.py.
"""

from __future__ import annotations

import contextlib
import os
import random
import threading
import time
from typing import Any, Dict, List, Optional

try:
    from opentelemetry import trace as _trace
    from opentelemetry.trace import (NonRecordingSpan, SpanContext,
                                     TraceFlags)
    _HAVE_OTEL = True
except ImportError:  # pragma: no cover - otel-api is in this image
    _trace = None
    _HAVE_OTEL = False

_TRACER_NAME = "ray_tpu"


def enable_tracing() -> bool:
    """Turn on span injection for this process and future workers."""
    if not _HAVE_OTEL:
        return False
    os.environ["RAY_TPU_OTEL"] = "1"
    return True


def disable_tracing() -> None:
    os.environ.pop("RAY_TPU_OTEL", None)


def is_enabled() -> bool:
    return _HAVE_OTEL and os.environ.get("RAY_TPU_OTEL") == "1"


def _tracer():
    return _trace.get_tracer(_TRACER_NAME)


def inject_context() -> Optional[str]:
    """Current span as a W3C ``traceparent`` string, or None."""
    if not is_enabled():
        return None
    span = _trace.get_current_span()
    ctx = span.get_span_context()
    if not ctx.is_valid:
        return None
    return (f"00-{ctx.trace_id:032x}-{ctx.span_id:016x}-"
            f"{int(ctx.trace_flags):02x}")


def _parse_traceparent(tp: str) -> Optional["SpanContext"]:
    try:
        _, trace_id, span_id, flags = tp.split("-")
        return SpanContext(
            trace_id=int(trace_id, 16), span_id=int(span_id, 16),
            is_remote=True, trace_flags=TraceFlags(int(flags, 16)))
    except (ValueError, AttributeError):
        return None


@contextlib.contextmanager
def span(name: str, traceparent: Optional[str] = None,
         attributes: Optional[Dict[str, Any]] = None):
    """A span, optionally parented to a remote ``traceparent`` (the
    worker-side half of cross-process propagation).  A present
    traceparent IS the enablement signal: workers don't share the
    driver's environment, the context shipped in the task spec is what
    says this task is traced."""
    if not is_enabled() and not traceparent:
        yield None
        return
    if not _HAVE_OTEL:
        yield None
        return
    ctx = None
    if traceparent:
        remote = _parse_traceparent(traceparent)
        if remote is not None:
            ctx = _trace.set_span_in_context(NonRecordingSpan(remote))
    with _tracer().start_as_current_span(
            name, context=ctx, attributes=attributes or {}) as sp:
        yield sp


def submit_span(function_name: str):
    """Driver-side submission span (reference: _inject_tracing_into_task)."""
    return span(f"task::{function_name} submit",
                attributes={"ray_tpu.function": function_name,
                            "ray_tpu.side": "driver"})


def execute_span(function_name: str, traceparent: Optional[str]):
    """Worker-side execution span, parented across the process boundary
    (reference: _inject_tracing_into_execution)."""
    return span(f"task::{function_name} execute", traceparent,
                attributes={"ray_tpu.function": function_name,
                            "ray_tpu.side": "worker",
                            "ray_tpu.pid": os.getpid()})


# ---------------------------------------------------------------- recorder


class _RecordedSpan(_trace.Span if _HAVE_OTEL else object):
    """Minimal recording span implementing the otel-api Span surface.
    MUST subclass the Span ABC: ``trace.get_current_span`` isinstance-
    checks it and returns INVALID_SPAN for duck-typed impostors."""

    def __init__(self, recorder: "SpanRecorder", name: str,
                 context: "SpanContext", parent_id: Optional[int],
                 attributes: Dict[str, Any]):
        self._recorder = recorder
        self.name = name
        self._context = context
        self.parent_id = parent_id
        self.attributes = dict(attributes)
        self.start_time = time.time()
        self.end_time: Optional[float] = None
        self.status: Optional[Any] = None

    # -- otel Span API ------------------------------------------------------
    def get_span_context(self):
        return self._context

    def set_attribute(self, key, value):
        self.attributes[key] = value

    def set_attributes(self, attributes):
        self.attributes.update(attributes)

    def add_event(self, *a, **kw):
        pass

    def add_link(self, *a, **kw):
        pass

    def update_name(self, name):
        self.name = name

    def is_recording(self) -> bool:
        return self.end_time is None

    def set_status(self, status, description=None):
        self.status = status

    def record_exception(self, exception, *a, **kw):
        self.attributes["exception.type"] = type(exception).__name__

    def end(self, end_time=None):
        if self.end_time is None:
            self.end_time = time.time()
            self._recorder._finished(self)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.end()


class _RecorderTracer(_trace.Tracer if _HAVE_OTEL else object):
    def __init__(self, recorder: "SpanRecorder"):
        self._recorder = recorder

    def start_span(self, name, context=None, kind=None, attributes=None,
                   links=None, start_time=None, record_exception=True,
                   set_status_on_exception=True) -> _RecordedSpan:
        parent = _trace.get_current_span(context).get_span_context()
        trace_id = (parent.trace_id if parent.is_valid
                    else random.getrandbits(128))
        parent_id = parent.span_id if parent.is_valid else None
        ctx = SpanContext(trace_id=trace_id,
                          span_id=random.getrandbits(64), is_remote=False,
                          trace_flags=TraceFlags(TraceFlags.SAMPLED))
        return _RecordedSpan(self._recorder, name, ctx, parent_id,
                             attributes or {})

    @contextlib.contextmanager
    def start_as_current_span(self, name, context=None, kind=None,
                              attributes=None, links=None, start_time=None,
                              record_exception=True,
                              set_status_on_exception=True,
                              end_on_exit=True):
        sp = self.start_span(name, context=context, attributes=attributes)
        token = _trace.context_api.attach(
            _trace.set_span_in_context(sp))
        try:
            yield sp
        finally:
            _trace.context_api.detach(token)
            if end_on_exit:
                sp.end()


class SpanRecorder(_trace.TracerProvider if _HAVE_OTEL else object):
    """In-memory TracerProvider substitute for images without the otel
    SDK.  ``SpanRecorder.install()`` registers it globally; finished
    spans accumulate in ``.spans`` (driver) or export via
    ``pop_serializable()`` for cross-process collection."""

    _installed: Optional["SpanRecorder"] = None

    def __init__(self):
        self.spans: List[_RecordedSpan] = []
        self._lock = threading.Lock()

    def _finished(self, span_obj: _RecordedSpan) -> None:
        with self._lock:
            self.spans.append(span_obj)

    # otel TracerProvider API
    def get_tracer(self, name, *a, **kw) -> _RecorderTracer:
        return _RecorderTracer(self)

    @classmethod
    def install(cls) -> "SpanRecorder":
        rec = cls()
        _trace.set_tracer_provider(rec)
        cls._installed = rec
        return rec

    def pop_serializable(self) -> List[Dict[str, Any]]:
        with self._lock:
            out = [{
                "name": s.name,
                "trace_id": f"{s.get_span_context().trace_id:032x}",
                "span_id": f"{s.get_span_context().span_id:016x}",
                "parent_id": (f"{s.parent_id:016x}"
                              if s.parent_id else None),
                "start": s.start_time, "end": s.end_time,
                "attributes": dict(s.attributes),
            } for s in self.spans if s.end_time is not None]
            self.spans.clear()
        return out
