"""Distributed FIFO queue (reference: `python/ray/util/queue.py`) backed by
a queue actor."""

from __future__ import annotations

import time
from typing import Any, List, Optional

from .. import api


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        self.maxsize = maxsize
        self._items: List[Any] = []

    def put(self, item) -> bool:
        if self.maxsize > 0 and len(self._items) >= self.maxsize:
            return False
        self._items.append(item)
        return True

    def get(self):
        if not self._items:
            return ("__empty__",)
        return (self._items.pop(0), None)

    def qsize(self) -> int:
        return len(self._items)


class Queue:
    def __init__(self, maxsize: int = 0, *, actor_options: dict = None):
        opts = dict(actor_options or {})
        opts.setdefault("num_cpus", 0.05)
        self._actor = api.remote(_QueueActor).options(**opts).remote(
            maxsize)

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ok = api.get(self._actor.put.remote(item), timeout=60.0)
            if ok:
                return
            if not block or (deadline and time.monotonic() > deadline):
                raise Full()
            time.sleep(0.01)

    def get(self, block: bool = True,
            timeout: Optional[float] = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            out = api.get(self._actor.get.remote(), timeout=60.0)
            if not (isinstance(out, tuple) and out[0] == "__empty__"):
                return out[0]
            if not block or (deadline and time.monotonic() > deadline):
                raise Empty()
            time.sleep(0.01)

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def qsize(self) -> int:
        return api.get(self._actor.qsize.remote(), timeout=60.0)

    def empty(self) -> bool:
        return self.qsize() == 0

    def shutdown(self) -> None:
        try:
            api.kill(self._actor)
        except Exception:
            pass
