"""Serializability inspector.

Capability mirror of the reference's
`ray.util.check_serialize.inspect_serializability`
(`python/ray/util/check_serialize.py`): recursively probe an object with
the framework serializer and report WHICH nested attribute/closure cell
fails, instead of surfacing one opaque pickling error from deep inside a
task submission.
"""

from __future__ import annotations

import inspect
from typing import Any, Set, Tuple

import cloudpickle


class FailTuple:
    """One leaf that failed: (name, parent object description)."""

    def __init__(self, name: str, parent: str):
        self.name = name
        self.parent = parent

    def __repr__(self):
        return f"FailTuple({self.name!r} found in {self.parent!r})"

    def __eq__(self, other):
        return (isinstance(other, FailTuple)
                and (self.name, self.parent) == (other.name, other.parent))

    def __hash__(self):
        return hash((self.name, self.parent))


def _serializable(obj: Any) -> bool:
    """Probe with the FRAMEWORK serializer, not raw cloudpickle — they
    diverge (core.serialization stages jax.Array to host memory via
    reducer_override and collects nested ObjectRefs), and the question
    this tool answers is 'can a task argument ship', not 'can pickle
    pickle it'."""
    try:
        from ..core import serialization
        serialization.serialize(obj, ref_collector=[])
        return True
    except Exception:
        pass
    try:
        # functions/classes ship via the function-table path
        cloudpickle.dumps(obj)
        return True
    except Exception:
        return False


def inspect_serializability(obj: Any, name: str = "object", *,
                            _depth: int = 3, _seen: Set[int] = None,
                            _print: bool = True
                            ) -> Tuple[bool, Set[FailTuple]]:
    """→ (ok, failures).  Walks closures, attributes, and containers of a
    non-serializable object to name the offending leaves."""
    _seen = _seen if _seen is not None else set()
    failures: Set[FailTuple] = set()
    if _serializable(obj):
        return True, failures
    if id(obj) in _seen or _depth <= 0:
        failures.add(FailTuple(name, type(obj).__name__))
        return False, failures
    _seen.add(id(obj))

    parent_desc = f"{name} ({type(obj).__name__})"
    children = []
    if inspect.isfunction(obj):
        if obj.__closure__:
            children += [(f"closure cell {v}", c.cell_contents)
                         for v, c in zip(
                             obj.__code__.co_freevars, obj.__closure__)]
        # referenced globals: only those the code object names
        gnames = getattr(obj.__code__, "co_names", ())
        g = getattr(obj, "__globals__", {})
        children += [(f"global {n}", g[n]) for n in gnames if n in g]
    elif isinstance(obj, dict):
        children = [(f"[{k!r}]", v) for k, v in list(obj.items())[:100]]
    elif isinstance(obj, (list, tuple, set)):
        children = [(f"[{i}]", v) for i, v in enumerate(list(obj)[:100])]
    elif hasattr(obj, "__dict__"):
        children = list(vars(obj).items())[:100]

    any_child_failed = False
    for cname, child in children:
        if _serializable(child):
            continue
        any_child_failed = True
        ok, sub = inspect_serializability(
            child, cname, _depth=_depth - 1, _seen=_seen, _print=False)
        if sub:
            failures |= sub
        else:
            failures.add(FailTuple(cname, parent_desc))
    if not any_child_failed:
        # the object itself is the unpicklable leaf
        failures.add(FailTuple(name, type(obj).__name__))
    if _print:
        for f in failures:
            print(f"  !!! FAIL serialization: {f}")
    return False, failures
