"""multiprocessing.Pool over runtime tasks (reference:
`python/ray/util/multiprocessing/pool.py`)."""

from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional

from .. import api


class AsyncResult:
    def __init__(self, refs, single: bool):
        self._refs = refs
        self._single = single

    def get(self, timeout: Optional[float] = None):
        out = api.get(self._refs, timeout=timeout or 600.0)
        return out[0] if self._single else out

    def ready(self) -> bool:
        ready, _ = api.wait(self._refs, num_returns=len(self._refs),
                            timeout=0)
        return len(ready) == len(self._refs)

    def wait(self, timeout: Optional[float] = None) -> None:
        api.wait(self._refs, num_returns=len(self._refs), timeout=timeout)


class Pool:
    """Process pool on cluster tasks; `processes` caps concurrency only in
    the scheduler sense (tasks queue beyond it)."""

    def __init__(self, processes: Optional[int] = None):
        self._task = api.remote(_call)

    def apply(self, fn: Callable, args: tuple = (), kwds: dict = None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn: Callable, args: tuple = (),
                    kwds: dict = None) -> AsyncResult:
        from ..core.serialization import dumps_function
        blob = dumps_function(fn)
        return AsyncResult([self._task.remote(blob, args, kwds or {})],
                           single=True)

    def map(self, fn: Callable, iterable: Iterable[Any]) -> List[Any]:
        return self.map_async(fn, iterable).get()

    def map_async(self, fn: Callable,
                  iterable: Iterable[Any]) -> AsyncResult:
        from ..core.serialization import dumps_function
        blob = dumps_function(fn)
        refs = [self._task.remote(blob, (x,), {}) for x in iterable]
        return AsyncResult(refs, single=False)

    def imap(self, fn: Callable, iterable: Iterable[Any]):
        from ..core.serialization import dumps_function
        blob = dumps_function(fn)
        refs = [self._task.remote(blob, (x,), {}) for x in iterable]
        for r in refs:
            yield api.get(r, timeout=600.0)

    def starmap(self, fn: Callable, iterable: Iterable[tuple]) -> List[Any]:
        from ..core.serialization import dumps_function
        blob = dumps_function(fn)
        refs = [self._task.remote(blob, tuple(args), {})
                for args in iterable]
        return api.get(refs, timeout=600.0)

    def close(self) -> None:
        pass

    def terminate(self) -> None:
        pass

    def join(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()


def _call(fn_blob: bytes, args: tuple, kwds: dict):
    from ..core.serialization import loads_function
    return loads_function(fn_blob)(*args, **kwds)
