"""multiprocessing.Pool over runtime tasks (reference:
`python/ray/util/multiprocessing/pool.py`).

Result waits are bounded: every ``get`` runs under a configurable
timeout (``mp_pool_default_timeout_s``, default 600 s, or the Pool's
``default_timeout_s`` override) and raises the typed ``GetTimeoutError``
— a lost result (worker crashed past its retries, object unreachable)
fails the caller promptly instead of hanging the pool."""

from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional

from .. import api
from ..core.config import GlobalConfig


def _resolve_timeout(timeout: Optional[float],
                     default: Optional[float] = None) -> float:
    if timeout is not None:
        return timeout
    if default is not None:
        return default
    return GlobalConfig.mp_pool_default_timeout_s


class AsyncResult:
    def __init__(self, refs, single: bool,
                 default_timeout_s: Optional[float] = None):
        self._refs = refs
        self._single = single
        self._default_timeout_s = default_timeout_s

    def get(self, timeout: Optional[float] = None):
        """Raises GetTimeoutError when the results don't arrive within
        ``timeout`` (default: the pool's / the mp_pool_default_timeout_s
        config)."""
        out = api.get(self._refs,
                      timeout=_resolve_timeout(timeout,
                                               self._default_timeout_s))
        return out[0] if self._single else out

    def ready(self) -> bool:
        ready, _ = api.wait(self._refs, num_returns=len(self._refs),
                            timeout=0)
        return len(ready) == len(self._refs)

    def wait(self, timeout: Optional[float] = None) -> None:
        api.wait(self._refs, num_returns=len(self._refs), timeout=timeout)


class Pool:
    """Process pool on cluster tasks; `processes` caps concurrency only in
    the scheduler sense (tasks queue beyond it).  ``default_timeout_s``
    overrides the config-level result-wait bound for this pool."""

    def __init__(self, processes: Optional[int] = None,
                 default_timeout_s: Optional[float] = None):
        self._task = api.remote(_call)
        self._default_timeout_s = default_timeout_s

    def _timeout(self, timeout: Optional[float] = None) -> float:
        return _resolve_timeout(timeout, self._default_timeout_s)

    def apply(self, fn: Callable, args: tuple = (), kwds: dict = None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn: Callable, args: tuple = (),
                    kwds: dict = None) -> AsyncResult:
        from ..core.serialization import dumps_function
        blob = dumps_function(fn)
        return AsyncResult([self._task.remote(blob, args, kwds or {})],
                           single=True,
                           default_timeout_s=self._default_timeout_s)

    def map(self, fn: Callable, iterable: Iterable[Any]) -> List[Any]:
        return self.map_async(fn, iterable).get()

    def map_async(self, fn: Callable,
                  iterable: Iterable[Any]) -> AsyncResult:
        from ..core.serialization import dumps_function
        blob = dumps_function(fn)
        refs = [self._task.remote(blob, (x,), {}) for x in iterable]
        return AsyncResult(refs, single=False,
                           default_timeout_s=self._default_timeout_s)

    def imap(self, fn: Callable, iterable: Iterable[Any]):
        from ..core.serialization import dumps_function
        blob = dumps_function(fn)
        refs = [self._task.remote(blob, (x,), {}) for x in iterable]
        for r in refs:
            yield api.get(r, timeout=self._timeout())

    def starmap(self, fn: Callable, iterable: Iterable[tuple]) -> List[Any]:
        from ..core.serialization import dumps_function
        blob = dumps_function(fn)
        refs = [self._task.remote(blob, tuple(args), {})
                for args in iterable]
        return api.get(refs, timeout=self._timeout())

    def close(self) -> None:
        pass

    def terminate(self) -> None:
        pass

    def join(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()


def _call(fn_blob: bytes, args: tuple, kwds: dict):
    from ..core.serialization import loads_function
    return loads_function(fn_blob)(*args, **kwds)
