"""Dask-graph scheduler over the task runtime.

Capability mirror of the reference's dask-on-ray scheduler
(`python/ray/util/dask/__init__.py`, `util/dask/scheduler.py` —
`dask.compute(..., scheduler=ray_dask_get)` runs every graph node as a
task).  dask itself is not in this image, so the graph *protocol* is
implemented here natively: a graph is a dict of ``key -> computation``
where a computation is a task tuple ``(callable, *args)``, a key
reference, a literal, or a (possibly nested) list of computations —
exactly dask's spec.  Each node becomes one cluster task; dependencies
pass as ObjectRefs, so independent branches execute in parallel and
intermediate results live in the object store, never the driver.

With dask installed, ``ray_dask_get`` plugs straight in as a dask
scheduler; without it, ``get`` executes hand-written or ported graphs.
"""

from __future__ import annotations

from typing import Any, Dict, Hashable, List, Sequence

from .. import api


def _ishashable(x: Any) -> bool:
    try:
        hash(x)
        return True
    except TypeError:
        return False


def istask(x: Any) -> bool:
    """A task tuple: non-empty tuple whose head is callable (dask spec)."""
    return isinstance(x, tuple) and bool(x) and callable(x[0])


def _resolve(expr: Any, refs: Dict[Hashable, Any], nested: List[Any]):
    """Rewrite a computation so task args referencing other keys become
    positional slots filled from ObjectRefs at execution time."""
    if istask(expr):
        return (expr[0],) + tuple(
            _resolve(a, refs, nested) for a in expr[1:])
    if _ishashable(expr) and expr in refs:
        nested.append(refs[expr])
        return _Slot(len(nested) - 1)
    if isinstance(expr, list):
        return [_resolve(e, refs, nested) for e in expr]
    return expr


class _Slot:
    """Placeholder for a dependency value delivered via ObjectRef."""

    __slots__ = ("i",)

    def __init__(self, i: int):
        self.i = i


def _execute_node(expr: Any, *dep_values: Any) -> Any:
    """Runs inside the worker: fill slots with resolved deps, then
    evaluate task tuples / lists recursively."""

    def ev(e: Any) -> Any:
        if isinstance(e, _Slot):
            return dep_values[e.i]
        if istask(e):
            return e[0](*[ev(a) for a in e[1:]])
        if isinstance(e, list):
            return [ev(x) for x in e]
        return e

    return ev(expr)


def _toposort(dsk: Dict[Hashable, Any]) -> List[Hashable]:
    deps = {k: _find_deps(v, dsk) for k, v in dsk.items()}
    out: List[Hashable] = []
    state: Dict[Hashable, int] = {}  # 1=visiting 2=done

    def visit(k: Hashable) -> None:
        st = state.get(k)
        if st == 2:
            return
        if st == 1:
            raise ValueError(f"cycle in task graph at {k!r}")
        state[k] = 1
        for d in deps[k]:
            visit(d)
        state[k] = 2
        out.append(k)

    for k in dsk:
        visit(k)
    return out


def _find_deps(expr: Any, dsk: Dict[Hashable, Any]) -> List[Hashable]:
    found: List[Hashable] = []

    def walk(e: Any) -> None:
        if istask(e):
            for a in e[1:]:
                walk(a)
        elif isinstance(e, list):
            for x in e:
                walk(x)
        elif _ishashable(e) and e in dsk:
            found.append(e)

    walk(expr)
    return found


@api.remote
def _graph_task(expr: Any, *dep_values: Any) -> Any:
    return _execute_node(expr, *dep_values)


def get(dsk: Dict[Hashable, Any], keys: Any, *,
        num_returns_timeout: float = 600.0) -> Any:
    """Execute a dask-spec graph; ``keys`` may be one key or a (nested)
    list of keys (dask's multiple-collection form)."""
    order = _toposort(dsk)
    refs: Dict[Hashable, Any] = {}
    for k in order:
        expr = dsk[k]
        nested: List[Any] = []
        resolved = _resolve(expr, refs, nested)
        if not istask(expr) and not nested and not isinstance(expr, list):
            # pure literal (or alias already handled via refs)
            refs[k] = api.put(expr)
            continue
        refs[k] = _graph_task.remote(resolved, *nested)

    def fetch(ks: Any) -> Any:
        if isinstance(ks, list):
            return [fetch(x) for x in ks]
        return api.get(refs[ks], timeout=num_returns_timeout)

    return fetch(keys)


def ray_dask_get(dsk: Dict[Hashable, Any], keys: Any, **kwargs) -> Any:
    """dask scheduler entry point: pass as ``scheduler=ray_dask_get`` to
    ``dask.compute`` (requires dask installed; the graph executor above
    carries the capability without it)."""
    return get(dict(dsk), keys)
