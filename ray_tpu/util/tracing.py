"""Cluster-wide task-lifecycle tracing (reference: core_worker/profiling.cc
profile events -> GCS, surfaced by `ray timeline` /
python/ray/_private/state.py:414 chrome_tracing_dump).

Two layers live here:

* ``profile`` — the legacy in-process Chrome-trace context manager
  (perf_counter clock, local buffer only).  Useful for driver-side
  micro-profiling; it never crosses a process boundary.

* **Distributed lifecycle spans** — every runtime process (driver,
  controller, nodelet, worker) appends spans for the hops of a task's
  life (submit → schedule → dequeue → fetch → exec → put, plus serve /
  train workload spans) into a bounded per-process buffer, stamped with
  the wall clock so cross-process merge lines up.  A per-process flush
  loop rewrites the buffer into the controller KV (namespace
  ``trace``, one key per process, ``persist=False`` so the WAL never
  sees it); ``state.timeline()`` merges every process's batch into one
  Chrome-trace JSON.  Overwrite semantics keep the controller's copy
  bounded: the KV holds "the recent spans of each process", nothing
  grows without bound.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ..core.config import GlobalConfig

TRACE_KV_NS = "trace"

_events: List[dict] = []
_lock = threading.Lock()


class profile:
    """Context manager recording one LOCAL Chrome-trace duration event.

    Both endpoints read ``time.perf_counter() * 1e6`` — one clock, one
    unit (µs).  (An earlier revision probed for a nonexistent
    ``time.perf_counter_us`` on enter, which would have mixed units with
    the exit path had it ever resolved.)
    """

    def __init__(self, name: str, category: str = "task"):
        self.name = name
        self.category = category

    def __enter__(self):
        self.start = time.perf_counter() * 1e6
        return self

    def __exit__(self, *exc):
        end = time.perf_counter() * 1e6
        with _lock:
            _events.append({
                "name": self.name, "cat": self.category, "ph": "X",
                "ts": self.start, "dur": max(0.0, end - self.start),
                "pid": os.getpid(), "tid": threading.get_ident() % 10000,
            })


def chrome_trace_events() -> List[dict]:
    with _lock:
        return list(_events)


# --------------------------------------------------- distributed spans

_span_lock = threading.Lock()
_spans: Optional[deque] = None
_dirty = False
_proc = {"kind": "proc", "node": ""}
_flusher_claimed = False


def configure(kind: str, node_id: str = "") -> None:
    """Set this process's identity for span attribution (called once by
    the driver core, worker runtime, nodelet, and controller)."""
    _proc["kind"] = kind
    _proc["node"] = (node_id or "")[:8]


def claim_flusher() -> bool:
    """First caller owns the KV flush loop for this process (a worker
    process hosts both a WorkerRuntime and a lazy CoreClient; only one
    may flush or they'd race on the dirty flag)."""
    global _flusher_claimed
    with _span_lock:
        if _flusher_claimed:
            return False
        _flusher_claimed = True
        return True


def release_flusher() -> None:
    """Claimant is shutting down (driver disconnect): let the NEXT
    runtime in this process own the flush loop again.  Without this, a
    process doing init() -> shutdown() -> init() (every test after the
    first in a pytest invocation) silently loses its span flusher and
    the second cluster's timeline never sees driver spans."""
    global _flusher_claimed
    with _span_lock:
        _flusher_claimed = False


def _buffer() -> deque:
    global _spans
    if _spans is None:
        _spans = deque(maxlen=max(16, GlobalConfig.trace_buffer_size))
    return _spans


def proc_label() -> str:
    node = _proc["node"]
    return f"{_proc['kind']}@{node}" if node else _proc["kind"]


def kv_key() -> str:
    return f"{_proc['kind']}:{_proc['node']}:{os.getpid()}"


def record_span(name: str, cat: str, start_s: float, end_s: float,
                **args: Any) -> None:
    """Record one lifecycle span (wall-clock seconds in, Chrome µs out)."""
    if not GlobalConfig.trace_enabled:
        return
    ev = {
        "name": name, "cat": cat, "ph": "X",
        "ts": start_s * 1e6, "dur": max(0.0, end_s - start_s) * 1e6,
        "pid": proc_label(), "tid": str(os.getpid()),
        "args": {k: v for k, v in args.items() if v},
    }
    global _dirty
    with _span_lock:
        _buffer().append(ev)
        _dirty = True


class span:
    """Context manager form of :func:`record_span` (wall clock)."""

    def __init__(self, name: str, cat: str = "task", **args: Any):
        self.name = name
        self.cat = cat
        self.args = args

    def __enter__(self):
        self.start = time.time()
        return self

    def __exit__(self, *exc):
        record_span(self.name, self.cat, self.start, time.time(),
                    **self.args)


def span_events() -> List[dict]:
    """Snapshot of this process's span buffer."""
    with _span_lock:
        return list(_buffer())


def kv_payload() -> Optional[bytes]:
    """The buffer as JSON bytes if anything changed since the last
    flush, else None.  Clears the dirty flag — callers whose flush RPC
    fails should :func:`mark_dirty` so the next tick retries."""
    global _dirty
    with _span_lock:
        if not _dirty:
            return None
        _dirty = False
        return json.dumps(list(_buffer())).encode()


def mark_dirty() -> None:
    global _dirty
    with _span_lock:
        _dirty = True


def cluster_trace_events() -> List[dict]:
    """Driver-local profile spans PLUS every process's flushed lifecycle
    spans PLUS every node's legacy finished-task spans — the flat-list
    form the dashboard consumes (``state.timeline()`` wraps the same
    spans, minus the differently-clocked local profile events, as a
    Chrome-trace dict)."""
    events = chrome_trace_events()
    try:
        from .. import state
        events += state._trace_span_events()
        events += state._node_task_span_events()
    except Exception:
        pass  # not connected / nodes unreachable: driver-local only
    return events


def dump_chrome_trace(path: str):
    with open(path, "w") as f:
        json.dump({"traceEvents": chrome_trace_events()}, f)
