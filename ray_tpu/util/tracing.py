"""Chrome-trace profiling events (reference: core_worker/profiling.cc +
python/ray/_private/state.py:414 chrome_tracing_dump).

Round-1 scope: in-process event collection; cross-process aggregation rides
the controller KV.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import List

_events: List[dict] = []
_lock = threading.Lock()


class profile:
    """Context manager recording one Chrome-trace duration event."""

    def __init__(self, name: str, category: str = "task"):
        self.name = name
        self.category = category

    def __enter__(self):
        self.start = time.perf_counter_us() if hasattr(time, "perf_counter_us") \
            else time.perf_counter() * 1e6
        return self

    def __exit__(self, *exc):
        end = time.perf_counter() * 1e6
        with _lock:
            _events.append({
                "name": self.name, "cat": self.category, "ph": "X",
                "ts": self.start, "dur": end - self.start,
                "pid": os.getpid(), "tid": threading.get_ident() % 10000,
            })


def chrome_trace_events() -> List[dict]:
    with _lock:
        return list(_events)


def cluster_trace_events() -> List[dict]:
    """Driver-local spans PLUS every node's finished-task spans (the
    reference's profile-event aggregation: core_worker/profiling.cc ->
    GCS -> `ray.timeline` chrome dump, _private/state.py:414)."""
    events = chrome_trace_events()
    try:
        from .. import state
        for n in state.list_nodes():
            if not n.get("alive"):
                continue
            for sp in state._node_call(n["addr"], "task_spans"):
                events.append({
                    "name": sp["name"], "cat": "task", "ph": "X",
                    "ts": sp["start"] * 1e6,
                    "dur": max(0.0, (sp["end"] - sp["start"])) * 1e6,
                    "pid": "node:" + n["id"][:8],
                    "tid": "worker:" + sp["worker_id"][:8],
                    "args": {"task_id": sp.get("task_id", ""),
                             "interrupted": sp.get("interrupted", False)},
                })
    except Exception:
        pass  # not connected / nodes unreachable: driver-local only
    return events


def dump_chrome_trace(path: str):
    with open(path, "w") as f:
        json.dump({"traceEvents": chrome_trace_events()}, f)
