"""joblib backend: run scikit-learn style Parallel() jobs on the cluster.

Capability mirror of the reference's `ray.util.joblib`
(`python/ray/util/joblib/__init__.py` `register_ray` +
`ray_backend.py` RayBackend): registers a joblib parallel backend whose
batches execute as framework tasks, so
``with joblib.parallel_backend("ray_tpu"): Parallel()(delayed(f)(x) ...)``
fans out across the cluster.  Implements joblib's modern submit/future
contract (`ParallelBackendBase.submit` + ``retrieve_result_callback``,
joblib >= 1.4); the future-like wraps an ObjectRef with a waiter thread
that fires joblib's completion callback.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional

import ray_tpu


@ray_tpu.remote
def _run_batch(pickled_batch: bytes):
    import cloudpickle as cp
    return cp.loads(pickled_batch)()


class _RefFuture:
    """Future-like over an ObjectRef (joblib drives it via
    add_done_callback + get); completion is delivered by the shared
    dispatcher, not a thread per future."""

    def __init__(self, ref):
        self._ref = ref
        self._lock = threading.Lock()
        self._cbs: List[Callable] = []
        self._result: Any = None
        self._exc: Optional[BaseException] = None
        self._done = threading.Event()
        _dispatcher().track(self)

    def _complete(self):
        try:
            # the ref is wait()-ready; the short timeout only guards a
            # ready-then-evicted race
            self._result = ray_tpu.get(self._ref, timeout=30.0)
        except BaseException as e:  # noqa: BLE001 - surfaced via get()
            self._exc = e
        self._finish()

    def _fail(self, exc: BaseException):
        self._exc = exc
        self._finish()

    def _finish(self):
        with self._lock:
            self._done.set()
            cbs, self._cbs = self._cbs, []
        for cb in cbs:
            cb(self)

    def add_done_callback(self, cb: Callable) -> None:
        with self._lock:
            if not self._done.is_set():
                self._cbs.append(cb)
                return
        cb(self)

    def get(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError("task did not complete in time")
        if self._exc is not None:
            raise self._exc
        return self._result

    result = get


class _Dispatcher:
    """One thread multiplexing completion of every outstanding batch via
    ray_tpu.wait — hundreds of in-flight joblib batches cost one waiter,
    not one blocked thread each."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pending: dict = {}   # ref -> _RefFuture
        self._wake = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="ray-tpu-joblib-dispatch")
        self._thread.start()

    def track(self, fut: "_RefFuture") -> None:
        with self._lock:
            self._pending[fut._ref] = fut
        self._wake.set()

    def _loop(self):
        import time as _time
        while True:
            with self._lock:
                refs = list(self._pending)
            if not refs:
                self._wake.wait(timeout=1.0)
                self._wake.clear()
                continue
            if not ray_tpu.is_initialized():
                # the cluster shut down under outstanding batches: fail
                # them (ray_tpu.wait from this daemon thread would
                # otherwise auto-BOOT a fresh cluster via init())
                self._fail_all(RuntimeError(
                    "ray_tpu shut down with joblib batches in flight"))
                continue
            try:
                ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=0.5)
            except Exception:
                _time.sleep(0.2)  # don't busy-spin a persistent failure
                ready = []
            for ref in ready:
                with self._lock:
                    fut = self._pending.pop(ref, None)
                if fut is not None:
                    fut._complete()

    def _fail_all(self, exc: BaseException) -> None:
        with self._lock:
            futs, self._pending = list(self._pending.values()), {}
        for fut in futs:
            fut._fail(exc)


_dispatcher_singleton: Optional[_Dispatcher] = None
_dispatcher_lock = threading.Lock()


def _dispatcher() -> _Dispatcher:
    global _dispatcher_singleton
    with _dispatcher_lock:
        if _dispatcher_singleton is None:
            _dispatcher_singleton = _Dispatcher()
        return _dispatcher_singleton


def register_ray_tpu() -> None:
    """Register the 'ray_tpu' joblib backend (call once per process)."""
    try:
        from joblib._parallel_backends import ParallelBackendBase
        from joblib.parallel import register_parallel_backend
    except ImportError as e:
        raise ImportError(
            "joblib is not available in this environment; "
            "register_ray_tpu() needs it") from e

    class _TpuBackend(ParallelBackendBase):
        """Batches become tasks; effective_n_jobs = cluster CPUs."""

        supports_retrieve_callback = True
        uses_threads = False
        supports_sharedmem = False

        def effective_n_jobs(self, n_jobs: int) -> int:
            if n_jobs == 0:
                raise ValueError("n_jobs == 0 in Parallel has no meaning")
            if n_jobs == 1:
                return 1
            try:
                total = int(ray_tpu.cluster_resources().get("CPU", 1))
            except Exception:
                total = 1
            if n_jobs is None:
                return total
            if n_jobs < 0:
                # joblib convention: -1 = all, -2 = all but one, ...
                return max(total + 1 + n_jobs, 1)
            return min(n_jobs, total)

        def submit(self, func, callback=None):
            import cloudpickle

            fut = _RefFuture(_run_batch.remote(cloudpickle.dumps(func)))
            if callback is not None:
                fut.add_done_callback(callback)
            return fut

        # joblib < 1.4 spelled it apply_async
        def apply_async(self, func, callback=None):
            return self.submit(func, callback)

        def retrieve_result_callback(self, out):
            return out.get()

        def abort_everything(self, ensure_ready: bool = True):
            if ensure_ready:
                self.configure(n_jobs=self.parallel.n_jobs,
                               parallel=self.parallel)

    register_parallel_backend("ray_tpu", _TpuBackend)
