"""Parallel iterators over actors.

Capability mirror of the reference's `ray.util.iter` (`python/ray/util/iter.py`):
a `ParallelIterator` is a set of iterator *shards*, each hosted by an actor,
with functional transforms (`for_each`/`filter`/`batch`/`flatten`) applied
lazily per shard and results gathered synchronously (round-robin across
shards) or asynchronously (whichever shard is ready).  Like the reference,
transforms are IMMUTABLE: each returns a new `ParallelIterator` sharing the
shard actors but carrying its own op pipeline.  Each gather materializes
its pipeline under a fresh token on the shard actors, so branched views of
one base iterator can be gathered concurrently (interleaved generators,
`union` of branches) without clobbering each other.  Built directly on
this framework's actors; `gather_async` uses `ray_tpu.wait` exactly as
the reference uses `ray.wait`.
"""

from __future__ import annotations

import itertools
import uuid
from typing import Any, Callable, Iterable, List, Tuple

import ray_tpu


class _IterShard:
    """Actor hosting one shard: a base iterable plus any number of live
    pipelines, keyed by gather token (ops live client-side so transforms
    stay immutable)."""

    def __init__(self, items: List[Any]):
        self._items = items
        self._pipelines: dict = {}

    def reset(self, token: str, ops: List[Tuple[str, Any]]) -> bool:
        it: Iterable[Any] = iter(self._items)
        for op, arg in ops:
            if op == "for_each":
                it = map(arg, it)
            elif op == "filter":
                it = filter(arg, it)
            elif op == "flatten":
                it = itertools.chain.from_iterable(it)
            elif op == "batch":
                it = self._batched(it, arg)
        self._pipelines[token] = it
        return True

    @staticmethod
    def _batched(it, n):
        buf = []
        for x in it:
            buf.append(x)
            if len(buf) == n:
                yield buf
                buf = []
        if buf:
            yield buf

    def next_item(self, token: str):
        it = self._pipelines.get(token)
        if it is None:
            return {"stop": True}
        try:
            return {"item": next(it)}
        except StopIteration:
            self._pipelines.pop(token, None)
            return {"stop": True}

    def drop(self, token: str) -> bool:
        self._pipelines.pop(token, None)
        return True


class ParallelIterator:
    """Sharded lazy iterator; transforms return new iterators."""

    def __init__(self, shards: List[Tuple[Any, Tuple[Tuple[str, Any], ...]]]):
        # [(shard_actor, ops applied to that shard)]
        self._shards = shards

    # -- constructors -------------------------------------------------------
    @staticmethod
    def from_items(items: List[Any], num_shards: int = 2
                   ) -> "ParallelIterator":
        chunks: List[List[Any]] = [[] for _ in range(num_shards)]
        for i, x in enumerate(items):
            chunks[i % num_shards].append(x)
        actor_cls = ray_tpu.remote(_IterShard)
        return ParallelIterator(
            [(actor_cls.remote(c), ()) for c in chunks])

    @staticmethod
    def from_range(n: int, num_shards: int = 2) -> "ParallelIterator":
        return ParallelIterator.from_items(list(range(n)), num_shards)

    # -- transforms (lazy, immutable) ---------------------------------------
    def _extend(self, op: str, arg) -> "ParallelIterator":
        return ParallelIterator(
            [(actor, ops + ((op, arg),)) for actor, ops in self._shards])

    def for_each(self, fn: Callable[[Any], Any]) -> "ParallelIterator":
        return self._extend("for_each", fn)

    def filter(self, fn: Callable[[Any], bool]) -> "ParallelIterator":
        return self._extend("filter", fn)

    def batch(self, n: int) -> "ParallelIterator":
        return self._extend("batch", n)

    def flatten(self) -> "ParallelIterator":
        return self._extend("flatten", None)

    def num_shards(self) -> int:
        return len(self._shards)

    # -- gathering ----------------------------------------------------------
    def _start(self) -> List[Tuple[Any, str]]:
        """Materialize this view's pipelines; one token PER ENTRY so a
        union whose sides share a shard actor gets two independent
        pipelines on it."""
        base = uuid.uuid4().hex
        entries = [(actor, f"{base}-{i}")
                   for i, (actor, _) in enumerate(self._shards)]
        ray_tpu.get([actor.reset.remote(tok, list(ops))
                     for (actor, ops), (_, tok)
                     in zip(self._shards, entries)])
        return entries

    def gather_sync(self) -> Iterable[Any]:
        """Round-robin across shards, preserving per-shard order."""
        entries = self._start()
        live = list(entries)
        try:
            while live:
                nxt: List[Any] = []
                for s, tok in live:
                    out = ray_tpu.get(s.next_item.remote(tok))
                    if out.get("stop"):
                        continue
                    nxt.append((s, tok))
                    yield out["item"]
                live = nxt
        finally:
            for actor, tok in entries:
                actor.drop.remote(tok)

    def gather_async(self) -> Iterable[Any]:
        """Yield from whichever shard finishes first (reference:
        gather_async's completion-order semantics via ray.wait)."""
        entries = self._start()
        pending = {s.next_item.remote(tok): (s, tok) for s, tok in entries}
        try:
            while pending:
                ready, _ = ray_tpu.wait(list(pending), num_returns=1)
                ref = ready[0]
                shard, tok = pending.pop(ref)
                out = ray_tpu.get(ref)
                if out.get("stop"):
                    continue
                pending[shard.next_item.remote(tok)] = (shard, tok)
                yield out["item"]
        finally:
            for actor, tok in entries:
                actor.drop.remote(tok)

    def take(self, n: int) -> List[Any]:
        return list(itertools.islice(self.gather_sync(), n))

    def union(self, other: "ParallelIterator") -> "ParallelIterator":
        """Concatenate shard sets; branches of one base may be unioned
        (each gather keeps per-entry pipelines, so a shard actor shared
        by both sides serves two independent token pipelines)."""
        return ParallelIterator(self._shards + other._shards)


from_items = ParallelIterator.from_items
from_range = ParallelIterator.from_range
