"""Parallel iterators over actors.

Capability mirror of the reference's `ray.util.iter` (`python/ray/util/iter.py`):
a `ParallelIterator` is a set of iterator *shards*, each hosted by an actor,
with functional transforms (`for_each`/`filter`/`batch`/`flatten`) applied
lazily per shard and results gathered synchronously (round-robin across
shards) or asynchronously (whichever shard is ready).  Built directly on
this framework's actors — shard state lives in `_IterShard` actors, and
`gather_async` uses `ray_tpu.wait` exactly as the reference uses
`ray.wait`.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


class _IterShard:
    """Actor hosting one shard: a base iterable + a transform pipeline."""

    def __init__(self, items: List[Any]):
        self._items = items
        self._ops: List[tuple] = []
        self._it = None

    def apply(self, op: str, fn_or_n) -> bool:
        self._ops.append((op, fn_or_n))
        return True

    def _build(self):
        it: Iterable[Any] = iter(self._items)
        for op, arg in self._ops:
            if op == "for_each":
                it = map(arg, it)
            elif op == "filter":
                it = filter(arg, it)
            elif op == "flatten":
                it = itertools.chain.from_iterable(it)
            elif op == "batch":
                it = self._batched(it, arg)
        return it

    @staticmethod
    def _batched(it, n):
        buf = []
        for x in it:
            buf.append(x)
            if len(buf) == n:
                yield buf
                buf = []
        if buf:
            yield buf

    def reset(self) -> bool:
        self._it = self._build()
        return True

    def next_item(self):
        if self._it is None:
            self.reset()
        try:
            return {"item": next(self._it)}
        except StopIteration:
            return {"stop": True}


class ParallelIterator:
    """Sharded lazy iterator; transforms fan out to every shard actor."""

    def __init__(self, shard_actors: List[Any]):
        self._shards = shard_actors

    # -- constructors -------------------------------------------------------
    @staticmethod
    def from_items(items: List[Any], num_shards: int = 2
                   ) -> "ParallelIterator":
        chunks: List[List[Any]] = [[] for _ in range(num_shards)]
        for i, x in enumerate(items):
            chunks[i % num_shards].append(x)
        actor_cls = ray_tpu.remote(_IterShard)
        return ParallelIterator(
            [actor_cls.remote(c) for c in chunks])

    @staticmethod
    def from_range(n: int, num_shards: int = 2) -> "ParallelIterator":
        return ParallelIterator.from_items(list(range(n)), num_shards)

    # -- transforms (lazy, per shard) ---------------------------------------
    def _apply(self, op: str, arg) -> "ParallelIterator":
        ray_tpu.get([s.apply.remote(op, arg) for s in self._shards])
        return self

    def for_each(self, fn: Callable[[Any], Any]) -> "ParallelIterator":
        return self._apply("for_each", fn)

    def filter(self, fn: Callable[[Any], bool]) -> "ParallelIterator":
        return self._apply("filter", fn)

    def batch(self, n: int) -> "ParallelIterator":
        return self._apply("batch", n)

    def flatten(self) -> "ParallelIterator":
        return self._apply("flatten", None)

    def num_shards(self) -> int:
        return len(self._shards)

    # -- gathering ----------------------------------------------------------
    def gather_sync(self) -> Iterable[Any]:
        """Round-robin across shards, preserving per-shard order."""
        ray_tpu.get([s.reset.remote() for s in self._shards])
        live = list(self._shards)
        while live:
            nxt: List[Any] = []
            for s in live:
                out = ray_tpu.get(s.next_item.remote())
                if out.get("stop"):
                    continue
                nxt.append(s)
                yield out["item"]
            live = nxt

    def gather_async(self) -> Iterable[Any]:
        """Yield from whichever shard finishes first (reference:
        gather_async's completion-order semantics via ray.wait)."""
        ray_tpu.get([s.reset.remote() for s in self._shards])
        pending = {s.next_item.remote(): s for s in self._shards}
        while pending:
            ready, _ = ray_tpu.wait(list(pending), num_returns=1)
            ref = ready[0]
            shard = pending.pop(ref)
            out = ray_tpu.get(ref)
            if out.get("stop"):
                continue
            pending[shard.next_item.remote()] = shard
            yield out["item"]

    def take(self, n: int) -> List[Any]:
        return list(itertools.islice(self.gather_sync(), n))

    def union(self, other: "ParallelIterator") -> "ParallelIterator":
        return ParallelIterator(self._shards + other._shards)


from_items = ParallelIterator.from_items
from_range = ParallelIterator.from_range
