"""Collective communication groups.

Capability mirror of the reference's `ray.util.collective`
(`python/ray/util/collective/collective.py:120-615`: named groups with
allreduce/allgather/reducescatter/broadcast/send/recv/barrier over NCCL or
Gloo).  TPU-native split:

  * **Accelerator tensors** never use this module imperatively — they sync
    as XLA collectives (psum/all_gather/ppermute) compiled into programs
    over the device mesh (`ray_tpu.parallel`).  `mesh_collective_hints`
    returns the in-jit equivalents for each op.
  * **Host arrays** (the Gloo role) go through a named rendezvous actor —
    the same detached-store pattern as the reference's
    `NCCLUniqueIDStore` (`nccl_collective_group.py:29-34`).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from .. import api

_groups: Dict[str, "_GroupClient"] = {}
_local = threading.local()


class _GroupActor:
    """Rendezvous + reduction state for one named group."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self._contrib: Dict[str, list] = {}
        self._ready: Dict[str, Any] = {}
        self._mailbox: Dict[str, Any] = {}

    def contribute(self, key: str, rank: int, value, op: str):
        entry = self._contrib.setdefault(key, [None] * self.world_size)
        entry[rank] = np.asarray(value)
        if all(v is not None for v in entry):
            if op == "sum" or op == "mean":
                out = np.sum(entry, axis=0)
                if op == "mean":
                    out = out / self.world_size
            elif op == "max":
                out = np.max(entry, axis=0)
            elif op == "min":
                out = np.min(entry, axis=0)
            elif op == "prod":
                out = np.prod(entry, axis=0)
            elif op == "gather":
                out = list(entry)
            else:
                raise ValueError(f"unknown reduce op {op!r}")
            self._ready[key] = out
            del self._contrib[key]
        return True

    def fetch(self, key: str):
        return self._ready.get(key, "__pending__")

    def post(self, key: str, value):
        self._mailbox[key] = np.asarray(value)
        return True

    def take(self, key: str):
        if key in self._mailbox:
            return self._mailbox.pop(key)
        return "__pending__"

    def peek(self, key: str):
        return self._mailbox.get(key, "__pending__")


class _GroupClient:
    def __init__(self, name: str, world_size: int, rank: int, handle):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.handle = handle
        self._counters: Dict[str, int] = {}

    def _key(self, tag: str) -> str:
        return f"{tag}/{self._seq(tag)}"

    def _seq(self, tag: str) -> int:
        n = self._counters.get(tag, 0)
        self._counters[tag] = n + 1
        return n

    def _await(self, getter, key: str, timeout_s: float):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            out = api.get(getter(key), timeout=timeout_s)
            if not (isinstance(out, str) and out == "__pending__"):
                return out
            time.sleep(0.004)
        raise TimeoutError(f"collective {key!r} timed out in {self.name}")

    def reduce(self, value, op: str, tag: str, timeout_s: float):
        key = self._key(tag)
        api.get(self.handle.contribute.remote(key, self.rank, value, op),
                timeout=timeout_s)
        return self._await(lambda k: self.handle.fetch.remote(k), key,
                           timeout_s)


def init_collective_group(world_size: int, rank: int, *,
                          backend: str = "host",
                          group_name: str = "default") -> None:
    """Join a named group.  ``backend="host"`` (numpy over a rendezvous
    actor); accelerator tensors use mesh collectives inside jit instead."""
    actor_name = f"collective::{group_name}"
    if rank == 0:
        handle = api.remote(_GroupActor).options(
            name=actor_name, get_if_exists=True,
            num_cpus=0.05).remote(world_size)
    else:
        # concurrent get_if_exists creation races; non-zero ranks wait for
        # rank 0's actor (the reference's unique-id-store rendezvous shape)
        deadline = time.monotonic() + 60.0
        while True:
            try:
                handle = api.get_actor(actor_name)
                break
            except Exception:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.02)
    _groups[group_name] = _GroupClient(group_name, world_size, rank, handle)


def destroy_collective_group(group_name: str = "default") -> None:
    g = _groups.pop(group_name, None)
    if g is not None and g.rank == 0:
        try:
            api.kill(g.handle)
        except Exception:
            pass


def _group(group_name: str) -> _GroupClient:
    if group_name not in _groups:
        raise RuntimeError(
            f"collective group {group_name!r} not initialized "
            "(call init_collective_group)")
    return _groups[group_name]


def allreduce(value, *, op: str = "sum", group_name: str = "default",
              timeout_s: float = 120.0):
    return _group(group_name).reduce(value, op, "ar", timeout_s)


def allgather(value, *, group_name: str = "default",
              timeout_s: float = 120.0) -> List[Any]:
    return _group(group_name).reduce(value, "gather", "ag", timeout_s)


def reducescatter(value, *, op: str = "sum", group_name: str = "default",
                  timeout_s: float = 120.0):
    """Reduce then return this rank's equal slice along axis 0."""
    g = _group(group_name)
    full = g.reduce(value, op, "rs", timeout_s)
    chunks = np.array_split(np.asarray(full), g.world_size, axis=0)
    return chunks[g.rank]


def broadcast(value, *, src_rank: int = 0, group_name: str = "default",
              timeout_s: float = 120.0):
    g = _group(group_name)
    key = g._key("bc")
    if g.rank == src_rank:
        api.get(g.handle.post.remote(key, value), timeout=timeout_s)
        return np.asarray(value)
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        out = api.get(g.handle.peek.remote(key), timeout=timeout_s)
        if not (isinstance(out, str) and out == "__pending__"):
            return out
        time.sleep(0.004)
    raise TimeoutError("broadcast timed out")


def send(value, dst_rank: int, *, group_name: str = "default",
         timeout_s: float = 120.0) -> None:
    g = _group(group_name)
    # Sequence per (src,dst) pair: the nth send from src to dst matches the
    # nth recv of dst from src, regardless of traffic to/from other peers or
    # asymmetric send/recv counts (a shared counter deadlocks those).
    key = f"p2p/{g.rank}->{dst_rank}/{g._seq(f'send:{dst_rank}')}"
    api.get(g.handle.post.remote(key, value), timeout=timeout_s)


def recv(src_rank: int, *, group_name: str = "default",
         timeout_s: float = 120.0):
    g = _group(group_name)
    key = f"p2p/{src_rank}->{g.rank}/{g._seq(f'recv:{src_rank}')}"
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        out = api.get(g.handle.take.remote(key), timeout=timeout_s)
        if not (isinstance(out, str) and out == "__pending__"):
            return out
        time.sleep(0.004)
    raise TimeoutError(f"recv from rank {src_rank} timed out")


def barrier(*, group_name: str = "default",
            timeout_s: float = 120.0) -> None:
    _group(group_name).reduce(np.zeros(()), "sum", "bar", timeout_s)


def mesh_collective_hints() -> Dict[str, str]:
    """The in-jit (compiled, ICI) equivalent for each imperative op."""
    return {
        "allreduce": "jax.lax.psum(x, axis_name)",
        "allgather": "jax.lax.all_gather(x, axis_name)",
        "reducescatter": "jax.lax.psum_scatter(x, axis_name)",
        "broadcast": "replicate via sharding (NamedSharding(mesh, P()))",
        "send/recv": "jax.lax.ppermute(x, axis_name, perm)",
        "alltoall": "jax.lax.all_to_all(x, axis_name, split, concat)",
    }
