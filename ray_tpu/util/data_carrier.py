"""Ship driver-side data to remote workers: object-store ref or inline.

ONE home for a rule two call sites (`tune.with_parameters`,
`air.BatchPredictor.predict`) previously each implemented with a latent
bug: `ray_tpu.put` only writes plasma above
``GlobalConfig.max_direct_call_object_size`` (100 KiB); smaller objects
live in the driver's PRIVATE in-process memory store, which remote
workers cannot fetch — a ref in that window, smuggled to a worker inside
an opaque pickled blob (where the nested-ref plasma promotion can't see
it), hangs the consumer forever.  Refs are therefore taken only when the
object CERTAINLY lands in plasma; everything else rides inline, which is
correct at any size (just unshared).
"""

from __future__ import annotations

from typing import Any, Tuple

Carrier = Tuple[str, Any]   # ("ref", ObjectRef) | ("inline", payload)


def _plasma_certain(approx_nbytes: int) -> bool:
    """Conservative 4x margin over the direct-call threshold: the size
    probe (cloudpickle) and the wire serializer (msgpack + pickle
    out-of-band) can disagree by small factors, and a ref that lands in
    the memory store is a worker hang, not a slowdown."""
    from ..core.config import GlobalConfig
    return approx_nbytes > 4 * GlobalConfig.max_direct_call_object_size


def store_bytes(blob: bytes) -> Carrier:
    # for raw bytes the wire size IS len(blob) + small framing, so no
    # probe margin is needed — just clear the direct-call threshold with
    # framing slack (the 4x margin would regress 100-400 KiB checkpoints
    # to per-task inline shipping)
    import ray_tpu
    from ..core.config import GlobalConfig
    if len(blob) > GlobalConfig.max_direct_call_object_size + 4096:
        return ("ref", ray_tpu.put(blob))
    return ("inline", blob)


def fetch_bytes(carrier: Carrier) -> bytes:
    kind, payload = carrier
    if kind == "ref":
        import ray_tpu
        return ray_tpu.get(payload)
    return payload


def _approx_nbytes(value: Any) -> int:
    """Cheap size estimate — a full cloudpickle probe of a multi-GB
    array would double peak memory for exactly the objects this module
    exists to ship.  Array-likes and bytes answer from metadata; only
    opaque objects pay for a pickle."""
    if isinstance(value, (bytes, bytearray)):
        return len(value)
    nbytes = getattr(value, "nbytes", None)
    # object-dtype arrays report 8-byte pointers, not payload — fall
    # through to the exact probe for those
    if isinstance(nbytes, int) and \
            str(getattr(value, "dtype", "")) != "object":
        return nbytes
    import cloudpickle
    return len(cloudpickle.dumps(value))


def store_value(value: Any) -> Carrier:
    """Like store_bytes but keeps VALUE semantics: large values are
    `put` directly (numpy rides the serializer's out-of-band buffers and
    reads back as zero-copy views from shm), small ones inline as-is."""
    import ray_tpu
    if _plasma_certain(_approx_nbytes(value)):
        return ("ref", ray_tpu.put(value))
    return ("inline", value)


def fetch_value(carrier: Carrier) -> Any:
    kind, payload = carrier
    if kind == "ref":
        import ray_tpu
        return ray_tpu.get(payload)
    return payload
