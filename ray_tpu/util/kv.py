"""Cluster-wide internal KV client (reference: GCS InternalKV,
`src/ray/gcs/gcs_server/gcs_kv_manager.cc`, Python surface
`ray.experimental.internal_kv`).  Backed by the controller's KV table."""

from __future__ import annotations

from typing import List, Optional

from ..api import _ensure_initialized


def _as_bytes(v) -> bytes:
    return v if isinstance(v, bytes) else str(v).encode()


def kv_put(key, value, *, namespace: str = "") -> None:
    core = _ensure_initialized()
    core.controller.call("kv_put", {
        "ns": namespace, "key": _as_bytes(key), "value": _as_bytes(value)})


def kv_get(key, *, namespace: str = "") -> Optional[bytes]:
    core = _ensure_initialized()
    return core.controller.call("kv_get", {
        "ns": namespace, "key": _as_bytes(key)})


def kv_del(key, *, namespace: str = "") -> bool:
    core = _ensure_initialized()
    return core.controller.call("kv_del", {
        "ns": namespace, "key": _as_bytes(key)})


def kv_exists(key, *, namespace: str = "") -> bool:
    core = _ensure_initialized()
    return core.controller.call("kv_exists", {
        "ns": namespace, "key": _as_bytes(key)})


def kv_keys(prefix=b"", *, namespace: str = "") -> List[bytes]:
    core = _ensure_initialized()
    return core.controller.call("kv_keys", {
        "ns": namespace, "prefix": _as_bytes(prefix)})
