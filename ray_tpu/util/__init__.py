"""Utility integrations over the core API (reference: `python/ray/util/`):
placement groups, scheduling strategies, collectives, actor pool, queue,
multiprocessing Pool, tracing, parallel iterators, joblib backend,
serializability inspection, remote debugger."""

from .actor_pool import ActorPool  # noqa: F401
from .check_serialize import inspect_serializability  # noqa: F401
from .placement_group import (  # noqa: F401
    placement_group,
    placement_group_table,
    remove_placement_group,
    tpu_slice_placement_group,
)
from .queue import Empty, Full, Queue  # noqa: F401
from .scheduling_strategies import (  # noqa: F401
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)
