"""ActorPool (reference: `python/ray/util/actor_pool.py`): load-balanced
work distribution over a fixed set of actors."""

from __future__ import annotations

from typing import Any, Callable, Iterable, List

from .. import api


class ActorPool:
    def __init__(self, actors: List[Any], *,
                 task_timeout_s: float = None):
        """``task_timeout_s``: optional per-task wall-clock bound.  The
        default is unbounded — pool tasks are arbitrary user work (a
        train step can legitimately run for hours) and dead actors
        surface through the actor-death path; set a bound to also catch
        wedged-but-alive workers (e.g. a hung device op)."""
        self._task_timeout_s = task_timeout_s
        self._idle = list(actors)
        self._future_to_actor = {}
        self._pending = []          # ordered (index, ref)
        self._next_task_index = 0
        self._next_return_index = 0
        self._results = {}

    def submit(self, fn: Callable, value: Any) -> None:
        if not self._idle:
            raise RuntimeError("no idle actors; call get_next first")
        actor = self._idle.pop(0)
        ref = fn(actor, value)
        self._future_to_actor[ref] = actor
        self._pending.append((self._next_task_index, ref))
        self._next_task_index += 1

    def has_next(self) -> bool:
        return bool(self._pending) or \
            self._next_return_index in self._results

    def has_free(self) -> bool:
        return bool(self._idle)

    def _collect(self, ref) -> Any:
        actor = self._future_to_actor.pop(ref)
        self._idle.append(actor)
        return api.get(ref, timeout=self._task_timeout_s)

    def get_next(self, timeout: float = None) -> Any:
        """Next result in submission order."""
        idx = self._next_return_index
        self._next_return_index += 1
        if idx in self._results:
            return self._results.pop(idx)
        while True:
            for i, (task_idx, ref) in enumerate(self._pending):
                if task_idx == idx:
                    del self._pending[i]
                    return self._collect(ref)
            raise RuntimeError(f"no pending task with index {idx}")

    def get_next_unordered(self, timeout: float = None) -> Any:
        if not self._pending:
            raise RuntimeError("no pending tasks")
        refs = [ref for _, ref in self._pending]
        ready, _ = api.wait(refs, num_returns=1, timeout=timeout)
        ref = ready[0]
        self._pending = [(i, r) for i, r in self._pending if r != ref]
        return self._collect(ref)

    _DONE = object()

    def _map_impl(self, fn: Callable, values: Iterable[Any],
                  next_result: Callable):
        it = iter(values)
        while True:
            if self._idle:
                v = next(it, self._DONE)
                if v is self._DONE:
                    break
                self.submit(fn, v)
            else:
                yield next_result()
        while self._pending or \
                self._next_return_index in self._results:
            yield next_result()

    def map(self, fn: Callable, values: Iterable[Any]):
        return self._map_impl(fn, values, self.get_next)

    def map_unordered(self, fn: Callable, values: Iterable[Any]):
        return self._map_impl(fn, values, self.get_next_unordered)
