"""Remote debugger: pdb over a TCP socket for worker processes.

Capability mirror of the reference's `ray.util.rpdb`
(`python/ray/util/rpdb.py`): a breakpoint inside a task/actor can't use
stdin (the worker's stdio goes to log files), so ``set_trace()`` binds a
localhost socket, registers the address in the controller KV (namespace
``rpdb``), and serves a full pdb session to whoever connects —
``ray_tpu debug``-style tooling or a raw ``nc host port``.
"""

from __future__ import annotations

import pdb
import socket
import sys
from typing import List, Optional, Tuple

_NS = "rpdb"
_trace_seq = 0


def _node_ip() -> str:
    """This host's outbound IP (UDP-connect trick; no packet is sent)."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"


class _SocketPdb(pdb.Pdb):
    """Pdb bound to an accepted TCP connection instead of stdio.

    The session's fds are closed when the user detaches: on quit always,
    and on continue when no breakpoints remain (tracing stops then, so
    the prompt can never come back and the fds would otherwise leak —
    one socket + one file object per breakpoint hit)."""

    def __init__(self, conn: socket.socket):
        self._conn = conn
        self._fh = conn.makefile("rw", buffering=1)
        super().__init__(stdin=self._fh, stdout=self._fh)
        self.use_rawinput = False
        self.prompt = "(rpdb) "

    def close(self):
        try:
            self._fh.close()
            self._conn.close()
        except OSError:
            pass

    def do_continue(self, arg):
        res = super().do_continue(arg)
        if not self.breaks:
            self.close()
        return res

    do_c = do_cont = do_continue

    def do_quit(self, arg):
        try:
            return super().do_quit(arg)
        finally:
            self.close()

    do_q = do_exit = do_quit

    def do_EOF(self, arg):
        try:
            return super().do_EOF(arg)
        finally:
            self.close()


def _announce(addr: Tuple[str, int], label: str) -> None:
    """Best-effort: register in the controller KV so `list_sessions` /
    CLI tooling can find waiting breakpoints."""
    try:
        from ..api import get_global_core
        core = get_global_core()
        core.controller.call("kv_put", {
            "ns": _NS, "key": label.encode(),
            "value": f"{addr[0]}:{addr[1]}".encode()})
    except Exception:
        pass


def _retract(label: str) -> None:
    """Remove the KV announcement once the breakpoint is no longer
    accepting (session over or accept timed out) — list_sessions must
    not accumulate dead addresses."""
    try:
        from ..api import get_global_core
        core = get_global_core()
        core.controller.call("kv_del", {"ns": _NS, "key": label.encode()})
    except Exception:
        pass


def set_trace(frame=None, *, port: int = 0,
              timeout_s: Optional[float] = 300.0) -> None:
    """Break here and wait (bounded) for a debugger client to connect.

    Prints/logs the address; if nobody connects within ``timeout_s`` the
    program continues instead of wedging a production task forever.
    """
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    # Bind the node's routable IP (NOT all interfaces — an unauthenticated
    # pdb socket is arbitrary code execution, so expose it no wider than
    # the cluster network) and announce that address: the breakpoint may
    # fire on a worker host while the operator connects from the head.
    try:
        srv.bind((_node_ip(), port))
    except OSError:
        srv.bind(("127.0.0.1", port))
    srv.listen(1)
    addr = srv.getsockname()
    import os
    import threading
    global _trace_seq
    _trace_seq += 1
    # unique per call: concurrent breakpoints in one process (threaded
    # actors) must not overwrite / retract each other's announcements
    label = f"pid-{os.getpid()}-t{threading.get_ident()}-{_trace_seq}"
    print(f"RPDB waiting on {addr[0]}:{addr[1]} "
          f"(connect: nc {addr[0]} {addr[1]})", file=sys.stderr, flush=True)
    _announce(addr, label)
    srv.settimeout(timeout_s)
    try:
        conn, _ = srv.accept()
    except (TimeoutError, socket.timeout):
        print("RPDB: no client connected; continuing", file=sys.stderr)
        srv.close()
        _retract(label)
        return
    srv.close()
    _retract(label)  # accepting now: the address is no longer joinable
    dbg = _SocketPdb(conn)
    dbg.set_trace(frame or sys._getframe().f_back)


def list_sessions() -> List[Tuple[str, str]]:
    """(label, host:port) of breakpoints currently waiting."""
    try:
        from ..api import get_global_core
        core = get_global_core()
        keys = core.controller.call("kv_keys", {"ns": _NS}) or []
        out = []
        for k in keys:
            v = core.controller.call("kv_get", {"ns": _NS, "key": k})
            if v:
                out.append((k.decode() if isinstance(k, bytes) else k,
                            v.decode() if isinstance(v, bytes) else v))
        return out
    except Exception:
        return []
