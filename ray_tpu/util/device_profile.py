"""Per-dispatch device profiling: the data-plane flight instruments.

PR-10's flight recorder made the control plane explainable after the
fact; this module does the same for the DATA plane.  Every registered
jitted program (decode step, prefill chunk, cache insert/gather,
draft/verify, train step) is wrapped ONCE in a timing shim that records,
per program:

* dispatch count and cumulative dispatch wall time (always);
* block-until-ready device time, sampled every Nth dispatch
  (``device_profile_sample_every``) so the hot loop stays hot — the
  estimate extrapolates the sampled mean over all dispatches;
* the argument-shape key of each dispatch, and the wall time of every
  FIRST-SEEN shape — the **compile ledger**.  A novel shape means XLA
  traces + compiles inside that dispatch, so its wall time is the
  observed compile cost and the recompile count is exactly the distinct
  shape count.  A ledger growing with traffic instead of staying O(1)
  is a compile storm — counted here, alerted via the nodelet's
  ``compile_storm`` flight-recorder trigger;
* tokens processed (host-known counts fed by the engine via
  :meth:`DispatchProfiler.note_tokens` — no device sync) and an
  analytic FLOPs-per-token figure (``models.decode_flops_per_token``),
  giving a roofline/MFU estimate per program:
  ``mfu = tokens * flops_per_token / device_seconds / peak_flops``.

The wrap is idempotent: wrapping an already-wrapped callable re-wraps
the ORIGINAL underneath, never stacking shims — critical because the
prefill chunk program is a module-level shared jit and every engine
(re)start wraps it again; stacking would double-count every dispatch.

Snapshots are cumulative plain dicts; the serve engine ships them on
its existing ``serve_metrics`` push and the nodelet folds deltas into
``ray_tpu_device_{dispatches,device_seconds,compile_seconds,compiles}``
counters and the ``ray_tpu_mfu_ratio`` gauge.

MFU caveat: peak FLOP/s comes from ``device_profile_peak_flops`` when
set, else a public-spec-sheet table by TPU device kind, else a nominal
CPU figure — on the CPU test harness the ratio is an indicative
utilization number, not a hardware truth.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

# bf16 peak TFLOP/s per chip by device kind (public spec sheets) —
# kept in sync with bench.py's table; longest prefix wins so
# "TPU v5p" is not shadowed by "TPU v5"
_PEAK_TFLOPS = {
    "TPU v4": 275.0,
    "TPU v5": 197.0,
    "TPU v5e": 197.0,
    "TPU v5p": 459.0,
    "TPU v6e": 918.0,
    "TPU v6 lite": 918.0,
}
#: nominal peak for non-TPU backends (CPU harness): a few hundred
#: GFLOP/s of fused f32 — makes the MFU gauge a meaningful relative
#: number in tests without pretending to be a spec sheet
_FALLBACK_PEAK = 2e11


def peak_flops() -> float:
    """Per-device peak FLOP/s: config override, else device-kind table,
    else the nominal fallback."""
    from ..core.config import GlobalConfig
    cfg = getattr(GlobalConfig, "device_profile_peak_flops", 0.0) or 0.0
    if cfg > 0:
        return float(cfg)
    try:
        import jax
        kind = getattr(jax.devices()[0], "device_kind", "")
    except Exception:
        kind = ""
    for key, tf in sorted(_PEAK_TFLOPS.items(),
                          key=lambda kv: -len(kv[0])):
        if kind.startswith(key):
            return tf * 1e12
    return _FALLBACK_PEAK


def _shape_key(args: tuple, kwargs: dict) -> tuple:
    """Cheap per-dispatch shape fingerprint: the shapes of TOP-LEVEL
    array arguments plus scalar statics.  Pytrees (params, caches) are
    summarized as ``*`` — walking them per dispatch would cost more
    than the dispatch; the dims that actually vary (token blocks,
    chunk widths, static ints) are all top-level here."""
    key: List[Any] = []
    for a in args:
        shape = getattr(a, "shape", None)
        if shape is not None:
            key.append(tuple(int(d) for d in shape))
        elif isinstance(a, (int, bool, str, float)):
            key.append(a)
        else:
            key.append("*")
    for k in sorted(kwargs):
        v = kwargs[k]
        key.append((k, getattr(v, "shape", None) or
                    (v if isinstance(v, (int, bool, str, float))
                     else "*")))
    return tuple(key)


class _ProgramStats:
    """Cumulative ledger of one wrapped program (single writer — the
    dispatching thread; snapshot readers tolerate torn reads)."""

    __slots__ = ("program", "dispatches", "wall_s", "sampled_s",
                 "sampled_n", "compile_s", "compiles", "shapes",
                 "tokens", "flops_per_token")

    def __init__(self, program: str):
        self.program = program
        self.dispatches = 0
        self.wall_s = 0.0
        self.sampled_s = 0.0        # block-until-ready sample total
        self.sampled_n = 0          # dispatches actually sampled
        self.compile_s = 0.0        # wall time of first-seen shapes
        self.compiles = 0           # distinct argument-shape keys seen
        self.shapes: set = set()
        self.tokens = 0
        self.flops_per_token = 0.0

    def device_seconds(self) -> float:
        """Extrapolated device time: sampled mean × all dispatches.
        Until the first sample lands, dispatch wall time is the bound
        (async dispatch makes it an underestimate, never zero)."""
        if self.sampled_n:
            return self.sampled_s * (self.dispatches
                                     / max(1, self.sampled_n))
        return self.wall_s

    def mfu(self, peak: float) -> Optional[float]:
        dev = self.device_seconds()
        if not self.flops_per_token or not self.tokens or dev <= 0 \
                or peak <= 0:
            return None
        return (self.tokens * self.flops_per_token) / dev / peak


class DispatchProfiler:
    """Wrap-once timing shims over a set of named jitted programs."""

    def __init__(self, sample_every: Optional[int] = None):
        self._sample_every = sample_every
        self._stats: Dict[str, _ProgramStats] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------ wiring
    def _stat(self, program: str) -> _ProgramStats:
        st = self._stats.get(program)
        if st is None:
            with self._lock:
                st = self._stats.setdefault(program,
                                            _ProgramStats(program))
        return st

    def _every(self) -> int:
        if self._sample_every is not None:
            return max(1, int(self._sample_every))
        from ..core.config import GlobalConfig
        return max(1, int(getattr(GlobalConfig,
                                  "device_profile_sample_every", 10)))

    def wrap(self, program: str, fn: Callable) -> Callable:
        """Return ``fn`` timed under ``program``.  Idempotent: a
        callable that is already a profiler shim (this profiler's or a
        previous engine incarnation's) is unwrapped to the original
        first, so re-registration after an engine restart never stacks
        two timers over one dispatch."""
        inner = getattr(fn, "_rt_profiled_inner", None)
        if inner is not None:
            fn = inner
        st = self._stat(program)

        def dispatch(*args, **kwargs):
            key = _shape_key(args, kwargs)
            novel = key not in st.shapes
            sample = novel or (st.dispatches + 1) % self._every() == 0
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            if sample:
                try:
                    import jax
                    out = jax.block_until_ready(out)
                except Exception:
                    pass
            dt = time.perf_counter() - t0
            st.dispatches += 1
            st.wall_s += dt
            if novel:
                # first dispatch of a shape pays trace + compile: its
                # wall time IS the observed compile cost (excluded from
                # the device-time sample pool so MFU is steady-state)
                st.shapes.add(key)
                st.compiles += 1
                st.compile_s += dt
            elif sample:
                st.sampled_s += dt
                st.sampled_n += 1
            return out

        dispatch._rt_profiled_inner = fn
        dispatch._rt_profiler = self
        dispatch.__name__ = getattr(fn, "__name__", program)
        return dispatch

    # ---------------------------------------------------------- feeding
    def note_tokens(self, program: str, n: int) -> None:
        """Credit ``n`` processed tokens to ``program`` — host-known
        counts (batch occupancy, chunk width) so the MFU numerator
        never costs a device sync."""
        if n > 0:
            self._stat(program).tokens += n

    def set_flops_per_token(self, program: str, flops: float) -> None:
        self._stat(program).flops_per_token = float(flops or 0.0)

    # --------------------------------------------------------- snapshot
    def wall_seconds(self) -> Dict[str, float]:
        """program -> cumulative dispatch wall seconds (the phase-
        attribution source: wall, not sampled device time, because the
        engine thread is occupied for the whole dispatch)."""
        with self._lock:
            return {p: s.wall_s for p, s in self._stats.items()}

    def distinct_shapes(self) -> int:
        with self._lock:
            return sum(len(s.shapes) for s in self._stats.values())

    def total_compiles(self) -> int:
        with self._lock:
            return sum(s.compiles for s in self._stats.values())

    def snapshot(self, peak: Optional[float] = None) -> List[dict]:
        """Cumulative per-program rows, wire-ready for the nodelet fold
        (every numeric travels cumulative; the nodelet incs deltas)."""
        pk = peak if peak is not None else peak_flops()
        rows = []
        with self._lock:
            stats = list(self._stats.values())
        for st in sorted(stats, key=lambda s: s.program):
            mfu = st.mfu(pk)
            rows.append({
                "program": st.program,
                "dispatches": st.dispatches,
                "wall_s": round(st.wall_s, 6),
                "device_s": round(st.device_seconds(), 6),
                "compile_s": round(st.compile_s, 6),
                "compiles": st.compiles,
                "shapes": len(st.shapes),
                "tokens": st.tokens,
                "mfu": None if mfu is None else round(mfu, 6),
            })
        return rows
