"""Deterministic fault injection (chaos) layer.

Failure is a first-class, *seeded* test input: a fault **plan** is a JSON
list of rules

    {"site": "rpc.send",                 # where to inject
     "match": {"nth": 3} | {"prob": 0.1, "seed": 7} | {"regex": "hb.*"}
              | {"peer": "ab12"},        # peer-directed sites only: fire
                                         #   only toward matching peers —
                                         #   severs A→B while B→A works
     "action": "drop",                   # what to do (site-dependent)
     "delay_s": 0.05,                    # for delay/latency + kill delays
     "once": true,                       # fire once CLUSTER-wide (claimed
                                         #   through the controller)
     "max_fires": 2,                     # per-process fire cap
     "proc": "worker"}                   # only in this process kind; a
                                         #   "nodelet:<node-id-prefix>"
                                         #   form pins the rule to ONE
                                         #   node's process (asymmetric
                                         #   partitions need a side)

distributed to every process via the controller KV (namespace ``chaos``,
pubsub channel ``chaos``, ``ray-tpu chaos apply``) or armed at bootstrap
from the ``chaos_plan`` config flag (``RAY_TPU_CHAOS_PLAN``), which the
existing config propagation ships to every spawned process.

Matchers are deterministic: ``nth`` fires on the Nth *eligible* hit of
the site in this process (regex filters which calls count as hits);
``prob`` draws from a per-rule ``random.Random(seed)`` whose sequence
replays identically run-to-run; ``regex`` matches the site key (RPC
method, function name, deployment name, object id hex).

Known sites (threaded through the runtime):

==========================  =====================================================
site                        actions
==========================  =====================================================
``rpc.send``                ``drop`` (frame lost), ``delay``, ``sever`` (close
                            the connection), ``error`` (raise RpcError)
``rpc.connect``             ``error``/``drop`` (connect refused), ``delay``
``nodelet.lease``           ``kill_worker`` (kill the just-granted worker after
                            ``delay_s`` — a gang/task worker dying mid-step)
``nodelet.heartbeat``       any action blackholes that heartbeat (partition)
``object.fetch_meta``       ``evict`` (drop the local copy + directory entry —
                            forces lineage reconstruction at the puller)
``worker.before_put``       ``crash`` (exit before the result reaches the
                            store: the task retries and re-executes),
                            ``delay``, ``error``
``worker.after_put``        same, after the result put (retry must be
                            idempotent against the already-stored object)
``worker.exec_crash``       ``sigkill``/``sigsegv``/``sigabrt`` signal-
                            kills the worker at task execution start
                            (key: function name) — a REAL signal death,
                            so the nodelet's death attributor classifies
                            it poison-shaped and the controller's crash
                            ledger counts it (the poison-wave e2e's
                            weapon); ``crash``/``error`` behave like the
                            ``worker.before_put`` variants
``nodelet.death_classify``  any action degrades the nodelet's death
                            attribution for that worker death (key:
                            worker id hex) to cause ``unknown`` —
                            proves the containment layer fails safe
                            when the classifier itself is attacked
                            (unknown is conservatively poison-shaped)
``serve.request``           ``crash`` (replica dies mid-request), ``error``,
                            ``delay``/``latency``
``serve.health_check``      ``error`` (health check fails)
``serve.session_failover``  attacks decode-stream RECOVERY itself
                            (serve/failover.py): ``error`` fails the
                            resume (the stream surfaces the in-band
                            error the failover would have hidden),
                            ``delay`` stretches the client-visible stall
``drain.evacuate``          any action fails that object's evacuation during a
                            node drain (the object rides the node to its death
                            and must come back via lineage reconstruction)
``drain.deadline``          any action forces the drain orchestrator to treat
                            the drain as deadline-overrun — the node takes the
                            hard-death recovery path immediately
``train.snapshot_put``      ``error``/``fail`` loses that elastic train
                            snapshot (the previous one stands — a repair's
                            lost-steps window widens by one interval),
                            ``delay`` stretches the off-step-path put
``train.repair_restore``    attacks elastic gang REPAIR itself
                            (train/backend_executor.py): ``error``/``fail``
                            aborts the repair — the run must take the
                            legacy full-restart-from-disk fallback;
                            ``delay`` stretches the repair window (the
                            double-failure tests land a second kill inside
                            it)
``controller.wal_replicate`` attacks the leader→standby WAL stream
                            (core/ha.py): ``drop`` loses a record batch
                            (the seq gap forces a snapshot resync; sync-
                            mode writes degrade to bounded-lag async
                            instead of stalling), ``delay`` stretches the
                            replication lag
``controller.lease_renew``  any action blackholes one leader→standby
                            lease renewal — enough in a row and the
                            standby promotes itself (forced failover
                            under a live TCP connection)
``object.transfer_fetch``   any action fails that cross-node object
                            fetch attempt at the PULLING nodelet (native
                            and chunked paths both) — with a ``peer``
                            matcher + a ``proc`` node pin this severs
                            the A→B transfer path only, driving the
                            alternate-path fetch ladder (retry →
                            alt copy → relay → lineage)
``nodelet.peer_probe``      any action makes that peer-reachability
                            probe report the peer unreachable — feeds
                            false negatives into the connectivity
                            matrix the suspect/quarantine logic folds
``controller.admission_shed`` ``force`` sheds the matched op (typed
                            ``_overload`` pushback) regardless of the
                            watermark state, ``suppress`` admits it even
                            under brownout — key is the op name.
                            Liveness-lane ops are never shed, forced or
                            not (core/overload.py pins the invariant)
``rpc.lane_starve``         ``delay``/``latency`` holds dispatch of
                            ONE priority lane (key: ``liveness`` |
                            ``control`` | ``bulk``) at the receiving
                            connection; a persistent rule THROTTLES
                            the lane to one dispatch per ``delay_s``
                            (an expired hold admits one item before
                            chaos re-evaluates) — proves the other
                            lanes keep flowing past a wedged one
``wal.append``              filesystem domain (key ``<dirname>:<op>``):
                            ``enospc``/``eio``/``error`` fails the WAL
                            record write — the store poisons itself and
                            the leader must self-fence (fsyncgate: after
                            one failed write the durable state is
                            unknowable)
``wal.fsync``               same, at the per-append fsync; ``delay`` is
                            a BLOCKING fsync stall (a dying disk hangs,
                            it does not return)
``wal.snapshot``            fails the compaction snapshot's tmp-write /
                            replace / dir-fsync dance — the WAL must
                            survive intact and replay
``spill.write``             ``enospc``/``eio``/``error`` fails that
                            object spill write (key: object id hex) —
                            proactive spill skips the object (it stays
                            in memory), capacity-pressure spill degrades
                            to in-memory retention + put backpressure
``spill.restore``           fails/corrupts that spill read — the copy is
                            treated as missing and the fetch ladder
                            falls through to alternates/lineage
``spill.delete``            fails the spill-file GC unlink (leaked file,
                            never a correctness fault)
``train.checkpoint_register`` fails the checkpoint commit dance
                            (train/checkpointing.py): the previous
                            checkpoint must stay loadable and the
                            caller gets a typed CheckpointWriteError
``flight.write``            fails the flight-recorder bundle write —
                            incident capture is best-effort: shed with
                            a counter, never an operator-visible error
==========================  =====================================================

Peer-directed sites (``rpc.send``, ``object.transfer_fetch``,
``nodelet.peer_probe``) evaluate an optional ``match.peer`` regex
against the remote side's label (dialed ``host:port`` for RPC, peer
node id for transfer/probe) — a rule can sever the A→B direction of a
link while B→A keeps working, the asymmetric partitions real networks
produce.

Zero-cost when disabled: every hot path guards with one module-level
``None`` check (``fi.ACTIVE is not None``, or the ``_chaos`` hook the
arm() call injects into ``core.rpc``/``core.worker_runtime``, which
cannot import this package at module scope without a cycle).  Every
injected fault increments ``ray_tpu_chaos_injected_total{site,action}``
(the counter is registered only while the layer is armed, so a clean
cluster's metrics never mention it) and records a ``chaos`` trace span
so the cluster timeline shows the fault *and* the recovery around it.
"""

from __future__ import annotations

import json
import random
import re
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from ..core.config import GlobalConfig
from . import tracing

CHAOS_KV_NS = "chaos"
CHAOS_KV_KEY = b"plan"
METRIC_NAME = "ray_tpu_chaos_injected_total"
CRASH_EXIT_CODE = 170  # distinguishable from user exits in worker logs

#: Filesystem sites all speak the same action set; error is a generic
#: injected OSError, enospc/eio carry the matching errno so callers'
#: errno-discriminating paths are exercised.
_FS_ACTIONS = frozenset({"error", "enospc", "eio"})

#: Every injection site threaded through the runtime, with the actions
#: that site understands (None = any action blackholes/fails the site).
#: ``delay``/``latency`` are universally valid.  `ray-tpu chaos
#: validate` lints plans against this registry so a typoed site or
#: action fails FAST instead of silently never firing.
KNOWN_SITES: Dict[str, Optional[frozenset]] = {
    "rpc.send": frozenset({"drop", "sever", "error"}),
    "rpc.connect": frozenset({"error", "drop"}),
    "nodelet.lease": frozenset({"kill_worker"}),
    "nodelet.heartbeat": None,
    "object.fetch_meta": frozenset({"evict"}),
    "worker.before_put": frozenset({"crash", "error"}),
    "worker.after_put": frozenset({"crash", "error"}),
    "worker.exec_crash": frozenset({"sigkill", "sigsegv", "sigabrt",
                                    "crash", "error"}),
    "nodelet.death_classify": None,
    "serve.request": frozenset({"crash", "error", "fail"}),
    "serve.health_check": frozenset({"error", "fail"}),
    "serve.session_failover": frozenset({"error", "fail"}),
    "serve.autoscale": frozenset({"drop", "error", "fail"}),
    "serve.spec_verify": frozenset({"error", "fail"}),
    "serve.slo_eval": frozenset({"error", "fail"}),
    "drain.evacuate": None,
    "drain.deadline": None,
    "train.snapshot_put": frozenset({"error", "fail"}),
    "train.repair_restore": frozenset({"error", "fail"}),
    "controller.wal_replicate": frozenset({"drop"}),
    "controller.lease_renew": None,
    "object.transfer_fetch": None,
    "nodelet.peer_probe": None,
    "controller.admission_shed": frozenset({"force", "suppress"}),
    "rpc.lane_starve": frozenset(),
    # Filesystem fault domain: error/enospc/eio raise OSError at the
    # site (delay/latency = a blocking stall — a dying disk hangs).
    "wal.append": _FS_ACTIONS,
    "wal.fsync": _FS_ACTIONS,
    "wal.snapshot": _FS_ACTIONS,
    "spill.write": _FS_ACTIONS,
    "spill.restore": _FS_ACTIONS,
    "spill.delete": _FS_ACTIONS,
    "train.checkpoint_register": _FS_ACTIONS,
    "flight.write": _FS_ACTIONS,
}
_UNIVERSAL_ACTIONS = frozenset({"delay", "latency"})
_RULE_KEYS = frozenset({"site", "action", "match", "delay_s", "once",
                        "max_fires", "proc", "id", "seed"})
_MATCH_KEYS = frozenset({"nth", "prob", "seed", "regex", "peer"})

#: The armed plan, or None when the chaos layer is disabled.  Hot paths
#: outside the import-cycle modules guard with ``fi.ACTIVE is not None``.
ACTIVE: Optional["FaultPlan"] = None

_lock = threading.Lock()
_counter = None            # metrics.Counter, registered only while armed
_local_claims: set = set()  # per-process fallback for `once` rules

# Modules whose hot paths cannot import this package at module scope
# (they sit below ray_tpu.util in the import graph); arm()/disarm() push
# the plan into their `_chaos` module global instead.
_HOOKED_MODULES = ("ray_tpu.core.rpc", "ray_tpu.core.worker_runtime")


class FaultRule:
    def __init__(self, idx: int, d: Dict[str, Any]):
        self.site = d["site"]
        self.action = d["action"]
        m = d.get("match") or {}
        self.nth = m.get("nth")
        self.prob = m.get("prob")
        self.regex = re.compile(m["regex"]) if m.get("regex") else None
        # peer-directed filter: only fire toward matching remote peers
        # (severs one DIRECTION of a link — asymmetric partitions)
        self.peer = re.compile(m["peer"]) if m.get("peer") else None
        self.seed = int(m.get("seed", d.get("seed", 0)))
        self.delay_s = float(d.get("delay_s", 0.05))
        self.max_fires = d.get("max_fires")
        self.once = bool(d.get("once"))
        self.proc = d.get("proc")
        self.rule_id = d.get("id") or f"{self.site}#{idx}"
        self._rng = random.Random(self.seed)
        self.hits = 0
        self.fires = 0

    def matches(self, key: str, proc_kind: str, proc_node: str = "",
                peer: str = "") -> bool:
        """One eligible hit of this rule's site; True when the fault
        fires.  Order matters for determinism: the regex/peer filters
        decide which calls count as hits, then nth/prob decide on the
        hit sequence."""
        if self.proc and not self._proc_matches(proc_kind, proc_node):
            return False
        if self.regex is not None and not self.regex.search(key or ""):
            return False
        if self.peer is not None and not self.peer.search(peer or ""):
            return False
        self.hits += 1
        if self.once and self.fires >= 1:
            return False
        if self.max_fires is not None and self.fires >= self.max_fires:
            return False
        if self.nth is not None:
            wanted = self.nth if isinstance(self.nth, (list, tuple)) \
                else (self.nth,)
            if self.hits not in wanted:
                return False
        if self.prob is not None and self._rng.random() >= self.prob:
            return False
        self.fires += 1
        return True

    def _proc_matches(self, proc_kind: str, proc_node: str) -> bool:
        """``proc`` filter: a bare kind ("nodelet") matches every process
        of that kind; ``"nodelet:<node-id-prefix>"`` pins the rule to
        the process running on ONE node (the tracing identity stores 8
        hex chars, so prefixes compare on their overlap)."""
        if ":" not in self.proc:
            return self.proc == proc_kind
        kind, _, pref = self.proc.partition(":")
        if kind != proc_kind or not pref or not proc_node:
            return False
        return pref.startswith(proc_node) or proc_node.startswith(pref)

    def to_act(self) -> Dict[str, Any]:
        return {"action": self.action, "delay_s": self.delay_s,
                "rule_id": self.rule_id, "once": self.once}


class FaultPlan:
    """Parsed plan; also the object injected into the hooked modules
    (they call ``point``/``async_point`` on it directly)."""

    def __init__(self, rules_json: List[Dict[str, Any]]):
        self.raw = [dict(r) for r in rules_json]
        self.rules: Dict[str, List[FaultRule]] = {}
        for i, d in enumerate(rules_json):
            r = FaultRule(i, d)
            self.rules.setdefault(r.site, []).append(r)

    def point(self, site: str, key: str = "",
              peer: str = "") -> Optional[Dict[str, Any]]:
        """Evaluate the plan at one injection site.  Returns the action
        dict when a rule fires (counting the metric and recording a
        trace span), else None.  Sync and loop-safe.  ``peer`` labels
        the remote side at peer-directed sites (dialed host:port, peer
        node id) for ``match.peer`` rules."""
        rules = self.rules.get(site)
        if not rules:
            return None
        kind = tracing._proc.get("kind", "")
        node = tracing._proc.get("node", "")
        for r in rules:
            with _lock:
                fired = r.matches(key, kind, node, peer)
            if fired:
                _count(site, r.action)
                now = time.time()
                tracing.record_span(f"chaos::{site}", "chaos", now, now,
                                    action=r.action, rule=r.rule_id,
                                    key=key)
                return r.to_act()
        return None

    async def async_point(self, site: str, key: str = "",
                          peer: str = "") -> Optional[Dict[str, Any]]:
        """``point`` for async sites: delay/latency actions sleep here
        (non-blocking); the action dict is returned either way so the
        caller applies drop/sever/error semantics itself."""
        act = self.point(site, key, peer)
        if act is not None and act["action"] in ("delay", "latency"):
            import asyncio
            await asyncio.sleep(max(0.0, act["delay_s"]))
        return act


# ----------------------------------------------------- filesystem domain

def fs_point(site: str, key: str = "") -> None:
    """Evaluate a filesystem chaos site; raises the injected ``OSError``
    (errno per action) or sleeps through a ``delay`` stall.

    Filesystem sites run in sync context (``asyncio.to_thread`` workers,
    the controller's deliberate fsync-per-append path), so the delay is
    a BLOCKING sleep — exactly what a stalling fsync does to its caller.
    """
    if ACTIVE is None:
        return
    act = ACTIVE.point(site, key)
    if act is None:
        return
    if act["action"] in _UNIVERSAL_ACTIONS:
        time.sleep(max(0.0, act["delay_s"]))
        return
    import errno
    import os
    eno = {"enospc": errno.ENOSPC, "eio": errno.EIO}.get(
        act["action"], errno.EIO)
    raise OSError(eno, f"chaos[{act['rule_id']}]: injected "
                       f"{os.strerror(eno)}", key or site)


# ----------------------------------------------------------- arm / disarm

def arm(plan: Any) -> "FaultPlan":
    """Arm the chaos layer in THIS process.  ``plan`` is the rule list
    (or its JSON text).  Re-arming replaces the plan and resets rule
    counters.  The plan is also written into GlobalConfig so processes
    THIS one spawns or registers later inherit it (a nodelet's
    register_worker reply ships its config snapshot — workers forked
    after a runtime `chaos apply` must still arm)."""
    global ACTIVE
    if isinstance(plan, (str, bytes)):
        plan = json.loads(plan)
    fp = FaultPlan(list(plan))
    with _lock:
        ACTIVE = fp
        _ensure_counter()
    try:
        GlobalConfig.update({"chaos_plan": json.dumps(fp.raw)})
    except KeyError:
        pass
    _sync_hooks(fp)
    return fp


def disarm() -> None:
    """Disable the layer and deregister its counter — a disabled cluster's
    metrics must not even mention the chaos metric."""
    global ACTIVE, _counter
    with _lock:
        ACTIVE = None
        if _counter is not None:
            from .. import metrics
            with metrics._lock:
                metrics._registry.pop(METRIC_NAME, None)
            _counter = None
        _local_claims.clear()
    try:
        import os
        GlobalConfig.update({"chaos_plan": ""})
        os.environ.pop("RAY_TPU_CHAOS_PLAN", None)
    except KeyError:
        pass
    _sync_hooks(None)


def maybe_arm_from_config() -> None:
    """Arm from the ``chaos_plan`` config flag (env-propagated to every
    spawned process) — no-op when empty or when already armed (so a late
    lazy CoreClient never resets a live plan's counters)."""
    if ACTIVE is not None:
        return
    raw = getattr(GlobalConfig, "chaos_plan", "") or ""
    if not raw:
        return
    try:
        arm(raw)
    except (ValueError, KeyError) as e:
        print(f"WARNING: ignoring malformed chaos plan: {e}",
              file=sys.stderr, flush=True)


def _sync_hooks(fp: Optional["FaultPlan"]) -> None:
    for name in _HOOKED_MODULES:
        mod = sys.modules.get(name)
        if mod is not None:
            mod._chaos = fp


def plan_snapshot() -> Optional[List[Dict[str, Any]]]:
    return list(ACTIVE.raw) if ACTIVE is not None else None


# ---------------------------------------------------------------- validation

def validate_plan(plan: Any) -> List[str]:
    """Lint a chaos plan; returns human-readable issues (empty = clean).

    A malformed plan mostly fails SILENTLY at runtime — an unknown site
    never fires, a bad regex raises at arm time in every process, two
    ``once`` rules sharing an id starve each other at the claim — so
    `ray-tpu chaos validate <plan.json>` runs these checks up front."""
    issues: List[str] = []
    if not isinstance(plan, list):
        return [f"plan must be a JSON list of rules, got "
                f"{type(plan).__name__}"]
    seen_ids: Dict[str, int] = {}
    for i, d in enumerate(plan):
        tag = f"rule #{i}"
        if not isinstance(d, dict):
            issues.append(f"{tag}: not an object "
                          f"({type(d).__name__})")
            continue
        site = d.get("site")
        if d.get("id"):
            tag = f"rule #{i} ({d['id']!r})"
        elif site:
            tag = f"rule #{i} ({site})"
        for k in d:
            if k not in _RULE_KEYS:
                issues.append(f"{tag}: unknown key {k!r} "
                              f"(known: {', '.join(sorted(_RULE_KEYS))})")
        if not site:
            issues.append(f"{tag}: missing 'site'")
        elif site not in KNOWN_SITES:
            issues.append(
                f"{tag}: unknown site {site!r} — the rule would never "
                f"fire (known: {', '.join(sorted(KNOWN_SITES))})")
        action = d.get("action")
        if not action:
            issues.append(f"{tag}: missing 'action'")
        elif site in KNOWN_SITES:
            allowed = KNOWN_SITES[site]
            if allowed is not None and action not in allowed \
                    and action not in _UNIVERSAL_ACTIONS:
                issues.append(
                    f"{tag}: action {action!r} is a no-op at site "
                    f"{site!r} (understood: "
                    f"{', '.join(sorted(allowed | _UNIVERSAL_ACTIONS))})")
        m = d.get("match")
        if m is not None and not isinstance(m, dict):
            issues.append(f"{tag}: 'match' must be an object")
            m = None
        if m:
            for k in m:
                if k not in _MATCH_KEYS:
                    issues.append(f"{tag}: unknown matcher {k!r} "
                                  f"(known: nth, prob, seed, regex, "
                                  f"peer)")
            if "nth" in m and "prob" in m:
                issues.append(f"{tag}: 'nth' and 'prob' conflict — one "
                              f"rule matches by count OR by draw, not "
                              f"both")
            nth = m.get("nth")
            if nth is not None and not (
                    isinstance(nth, int) and not isinstance(nth, bool)
                    or (isinstance(nth, (list, tuple)) and nth and all(
                        isinstance(n, int) and not isinstance(n, bool)
                        for n in nth))):
                issues.append(f"{tag}: 'nth' must be an int or a "
                              f"non-empty list of ints, got {nth!r}")
            prob = m.get("prob")
            if prob is not None and not (
                    isinstance(prob, (int, float))
                    and not isinstance(prob, bool) and 0 < prob <= 1):
                issues.append(f"{tag}: 'prob' must be in (0, 1], got "
                              f"{prob!r}")
            if m.get("regex") is not None:
                try:
                    re.compile(m["regex"])
                except (re.error, TypeError) as e:
                    issues.append(f"{tag}: bad regex "
                                  f"{m.get('regex')!r}: {e}")
            if m.get("peer") is not None:
                try:
                    re.compile(m["peer"])
                except (re.error, TypeError) as e:
                    issues.append(f"{tag}: bad peer matcher "
                                  f"{m.get('peer')!r}: {e}")
        delay = d.get("delay_s")
        if delay is not None and (not isinstance(delay, (int, float))
                                  or isinstance(delay, bool)
                                  or delay < 0):
            issues.append(f"{tag}: 'delay_s' must be a non-negative "
                          f"number, got {delay!r}")
        mf = d.get("max_fires")
        if mf is not None and (not isinstance(mf, int)
                               or isinstance(mf, bool) or mf < 1):
            issues.append(f"{tag}: 'max_fires' must be a positive int, "
                          f"got {mf!r}")
        if d.get("once") and isinstance(mf, int) and mf > 1:
            issues.append(f"{tag}: 'once' conflicts with max_fires="
                          f"{mf} — once caps the rule at one fire "
                          f"cluster-wide")
        rid = d.get("id")
        if rid:
            if rid in seen_ids:
                issues.append(
                    f"{tag}: duplicate rule id {rid!r} (also rule "
                    f"#{seen_ids[rid]}) — `once` claims are keyed by "
                    f"id, so duplicates starve each other and at most "
                    f"one ever fires")
            else:
                seen_ids[rid] = i
    return issues


# ------------------------------------------------------------------ metric

def _ensure_counter():
    global _counter
    if _counter is None:
        from .. import metrics
        _counter = metrics.Counter(
            METRIC_NAME,
            "Faults injected by the chaos layer", ("site", "action"))
    return _counter


def _count(site: str, action: str) -> None:
    c = _ensure_counter()
    c.inc(tags={"site": site, "action": action})


def count_injection(site: str, action: str) -> None:
    """Record an injection observed REMOTELY (a crashing worker's
    last-gasp notify lands in its nodelet's registry — worker registries
    are never scraped, and the process is gone a millisecond later)."""
    _count(site, action)


def injected_counts() -> Dict[str, float]:
    """site|action -> count for this process (chaos status CLI)."""
    if _counter is None:
        return {}
    return {"|".join(k): v for k, v in _counter._samples()}


# ------------------------------------------------------------- once claims

def local_claim(rule_id: str) -> bool:
    """Per-process `once` fallback when no controller is reachable."""
    with _lock:
        if rule_id in _local_claims:
            return False
        _local_claims.add(rule_id)
        return True


def chaos_env(plan: List[Dict[str, Any]]) -> Dict[str, str]:
    """Env block that arms spawned processes with ``plan`` (the
    cluster_utils / add_node(env=...) plumbing)."""
    return {"RAY_TPU_CHAOS_PLAN": json.dumps(plan)}
