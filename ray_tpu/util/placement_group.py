"""Placement groups: gang resource reservation across nodes.

API mirror of the reference (python/ray/util/placement_group.py:130-146,
strategies PACK | SPREAD | STRICT_PACK | STRICT_SPREAD) over the controller's
2-phase bundle commit.  The TPU-native extension: ``tpu_topology`` bundles
that reserve whole ICI sub-meshes (``TPU`` chips colocated per host) so a
multi-host SPMD gang lands on one contiguous slice.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..api import _ensure_initialized
from ..core.ids import PlacementGroupID

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]],
                 strategy: str):
        self.id = pg_id
        self.bundle_specs = bundles
        self.strategy = strategy

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        core = _ensure_initialized()
        reply = core.controller.call(
            "wait_placement_group",
            {"pg_id": self.id.binary(), "timeout": timeout_seconds},
            timeout=timeout_seconds + 10)
        return reply.get("state") == "CREATED"

    def ready(self, timeout_seconds: float = 60.0) -> "PlacementGroup":
        if not self.wait(timeout_seconds):
            raise TimeoutError(
                f"placement group {self.id.hex()[:12]} not ready "
                f"after {timeout_seconds}s")
        return self

    def table(self) -> dict:
        core = _ensure_initialized()
        for entry in core.controller.call("list_placement_groups"):
            if entry["pg_id"] == self.id.binary():
                return entry
        return {}

    def bundle_node_ids(self) -> List[str]:
        return self.table().get("node_ids", [])

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundle_specs, self.strategy))


def placement_group(bundles: List[Dict[str, float]], strategy: str = "PACK",
                    name: str = "") -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty dicts")
    core = _ensure_initialized()
    pg_id = PlacementGroupID.of(core.job_id)
    core.controller.call("create_placement_group", {
        "pg_id": pg_id.binary(),
        "bundles": [{k: float(v) for k, v in b.items()} for b in bundles],
        "strategy": strategy, "name": name})
    return PlacementGroup(pg_id, bundles, strategy)


def tpu_slice_placement_group(num_hosts: int, chips_per_host: int = 4,
                              cpus_per_host: float = 1.0,
                              strict: bool = True) -> PlacementGroup:
    """Reserve a TPU slice as one gang: ``num_hosts`` bundles of
    ``chips_per_host`` TPU chips, spread across distinct hosts so each bundle
    maps to one host's ICI-attached chips."""
    bundles = [{"TPU": float(chips_per_host), "CPU": cpus_per_host}
               for _ in range(num_hosts)]
    return placement_group(bundles,
                           strategy="STRICT_SPREAD" if strict else "SPREAD")


def remove_placement_group(pg: PlacementGroup):
    core = _ensure_initialized()
    core.controller.call("remove_placement_group", {"pg_id": pg.id.binary()})


def placement_group_table() -> List[dict]:
    core = _ensure_initialized()
    return core.controller.call("list_placement_groups")
