"""KubeRay-style node provider: scale a RayCluster custom resource.

Capability mirror of the reference's KubeRay provider
(/root/reference/python/ray/autoscaler/_private/kuberay/node_provider.py:204
— goal-state design: scale-up patches a worker group's ``replicas``,
scale-down patches ``replicas`` AND names the exact pods in
``scaleStrategy.workersToDelete``; the operator reconciles pods).  The
Kubernetes API surface is one injected callable
``api(method, path, body=None) -> dict`` so contract tests run against
recorded-response fakes; the default binding reads the in-cluster
service-account token like the reference's ``load_k8s_secrets``.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Optional

from .node_provider import NodeProvider

#: label keys the KubeRay operator stamps on pods (reference constants
#: KUBERAY_LABEL_KEY_KIND / KUBERAY_LABEL_KEY_TYPE)
LABEL_KIND = "ray.io/node-type"
LABEL_GROUP = "ray.io/group"
LABEL_CLUSTER = "ray.io/cluster"


def _default_api(namespace: str) -> Callable[..., dict]:
    """In-cluster REST binding via the mounted service account
    (reference: load_k8s_secrets + url_from_resource)."""
    token_path = "/var/run/secrets/kubernetes.io/serviceaccount/token"
    try:
        with open(token_path) as f:
            token = f.read()
    except OSError as exc:
        raise RuntimeError(
            "KubeRayProvider needs to run in-cluster (no service "
            "account token found) — or inject api= with a "
            "(method, path, body) callable") from exc
    import requests

    def api(method: str, path: str, body: Any = None) -> dict:
        base = "https://kubernetes.default:443"
        headers = {"Authorization": f"Bearer {token}"}
        if method == "PATCH":
            headers["Content-Type"] = "application/json-patch+json"
        r = requests.request(
            method, base + path, headers=headers,
            data=json.dumps(body) if body is not None else None,
            verify="/var/run/secrets/kubernetes.io/serviceaccount/"
                   "ca.crt")
        r.raise_for_status()
        return r.json()

    return api


class KubeRayProvider(NodeProvider):
    """Scale worker groups of a RayCluster CR; pods are the nodes.

    ``create_node`` bumps the group's goal replicas and returns a
    goal-state token (the operator names the pod); live node ids come
    from ``non_terminated_nodes``, which lists the cluster's worker
    pods — so a freshly requested node becomes visible once the
    operator schedules it, exactly the reference's batching-provider
    observable behavior.
    """

    def __init__(self, *, namespace: str, cluster_name: str,
                 api: Optional[Callable[..., dict]] = None):
        self.namespace = namespace
        self.cluster_name = cluster_name
        self._api = api if api is not None else _default_api(namespace)
        # goal tokens handed out by create_node that the operator has
        # not yet satisfied with a pod; listed as pending nodes so the
        # autoscaler's in-flight accounting sees them (without this,
        # every tick re-launches while the operator schedules)
        self._goals: Dict[str, Dict[str, Any]] = {}

    # -- CR access -----------------------------------------------------------
    def _cr_path(self) -> str:
        return (f"/apis/ray.io/v1/namespaces/{self.namespace}"
                f"/rayclusters/{self.cluster_name}")

    def _get_cr(self) -> dict:
        return self._api("GET", self._cr_path())

    def _group_index(self, cr: dict, node_type: str) -> int:
        groups = cr["spec"]["workerGroupSpecs"]
        for i, g in enumerate(groups):
            if g["groupName"] == node_type:
                return i
        raise ValueError(
            f"worker group {node_type!r} not in RayCluster "
            f"{self.cluster_name!r} (has: "
            f"{[g['groupName'] for g in groups]})")

    @property
    def node_types(self) -> Dict[str, Dict[str, Any]]:
        """Group name → spec, read from the CR (the CR is the config
        source of truth under KubeRay, not provider kwargs)."""
        cr = self._get_cr()
        return {g["groupName"]: g
                for g in cr["spec"]["workerGroupSpecs"]}

    def set_node_type(self, name: str, shape: Dict[str, Any]) -> None:
        """No-op: worker shapes are the CR's workerGroupSpecs — YAML
        shapes from `ray-tpu up` don't apply here."""

    # -- provider contract ---------------------------------------------------
    def node_resources(self, node_type: str) -> Dict[str, float]:
        cr = self._get_cr()
        g = cr["spec"]["workerGroupSpecs"][self._group_index(
            cr, node_type)]
        try:
            requests_ = g["template"]["spec"]["containers"][0][
                "resources"]["requests"]
        except (KeyError, IndexError):
            return {"CPU": 1.0}
        out: Dict[str, float] = {}
        cpu = requests_.get("cpu")
        if cpu is not None:
            s = str(cpu)
            out["CPU"] = float(s[:-1]) / 1000.0 if s.endswith("m") \
                else float(s)
        tpu = requests_.get("google.com/tpu")
        if tpu is not None:
            out["TPU"] = float(tpu)
        return out or {"CPU": 1.0}

    def create_node(self, node_type: str) -> str:
        cr = self._get_cr()
        idx = self._group_index(cr, node_type)
        replicas = int(cr["spec"]["workerGroupSpecs"][idx].get(
            "replicas", 0))
        # op "add" replaces an existing object member AND creates a
        # missing one (RFC 6902) — "replace" 422s on CRs that omit the
        # optional replicas/scaleStrategy fields
        self._api("PATCH", self._cr_path(), [{
            "op": "add",
            "path": f"/spec/workerGroupSpecs/{idx}/replicas",
            "value": replicas + 1,
        }])
        token = f"goal:{node_type}:{replicas + 1}"
        self._goals[token] = {"group": node_type,
                              "target": replicas + 1}
        return token

    def terminate_node(self, provider_node_id: str) -> None:
        """Scale-down protocol: name the pod in workersToDelete AND
        drop replicas in ONE patch (reference: worker_delete_patch +
        worker_replica_patch submitted together — separate patches race
        the operator into deleting an arbitrary pod)."""
        if provider_node_id.startswith("goal:"):
            # a never-materialized goal token: just lower the goal
            node_type = provider_node_id.split(":")[1]
            cr = self._get_cr()
            idx = self._group_index(cr, node_type)
            replicas = int(cr["spec"]["workerGroupSpecs"][idx].get(
                "replicas", 0))
            self._api("PATCH", self._cr_path(), [{
                "op": "add",
                "path": f"/spec/workerGroupSpecs/{idx}/replicas",
                "value": max(replicas - 1, 0),
            }])
            self._goals.pop(provider_node_id, None)
            return
        pod = self._api(
            "GET", f"/api/v1/namespaces/{self.namespace}/pods/"
                   f"{provider_node_id}")
        group = pod["metadata"]["labels"][LABEL_GROUP]
        cr = self._get_cr()
        idx = self._group_index(cr, group)
        spec = cr["spec"]["workerGroupSpecs"][idx]
        replicas = int(spec.get("replicas", 0))
        existing = (spec.get("scaleStrategy") or {}).get(
            "workersToDelete") or []
        self._api("PATCH", self._cr_path(), [
            {"op": "add",
             "path": f"/spec/workerGroupSpecs/{idx}/replicas",
             "value": max(replicas - 1, 0)},
            {"op": "add",
             "path": f"/spec/workerGroupSpecs/{idx}/scaleStrategy",
             "value": {"workersToDelete":
                       [*existing, provider_node_id]}},
        ])

    def non_terminated_nodes(self) -> List[str]:
        pods = self._api(
            "GET", f"/api/v1/namespaces/{self.namespace}/pods"
                   f"?labelSelector={LABEL_CLUSTER}="
                   f"{self.cluster_name}")
        out = []
        per_group: Dict[str, int] = {}
        for pod in pods.get("items", []):
            labels = pod["metadata"].get("labels", {})
            if labels.get(LABEL_KIND) == "head":
                continue
            if pod.get("status", {}).get("phase") in ("Running",
                                                      "Pending"):
                out.append(pod["metadata"]["name"])
                group = labels.get(LABEL_GROUP, "")
                per_group[group] = per_group.get(group, 0) + 1
        # unsatisfied goal tokens count as pending nodes so launch
        # accounting converges; a token retires once the operator has
        # materialized its target pod count OR the goal itself has been
        # lowered below the target (a later scale-down cancelled it —
        # without this the phantom 'pending' node lives forever)
        if self._goals:
            cr = self._get_cr()
            goal_replicas = {g["groupName"]: int(g.get("replicas", 0))
                             for g in cr["spec"]["workerGroupSpecs"]}
            for token, goal in list(self._goals.items()):
                if per_group.get(goal["group"], 0) >= goal["target"] \
                        or goal_replicas.get(goal["group"], 0) \
                        < goal["target"]:
                    del self._goals[token]
                else:
                    out.append(token)
        return out

    def node_type_of(self, node_id: str) -> Optional[str]:
        if node_id.startswith("goal:"):
            return node_id.split(":")[1]
        try:
            pod = self._api(
                "GET", f"/api/v1/namespaces/{self.namespace}/pods/"
                       f"{node_id}")
        except Exception:
            return None
        return pod["metadata"].get("labels", {}).get(LABEL_GROUP)
