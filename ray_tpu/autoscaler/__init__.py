"""Autoscaler: node-count reconciliation against resource demand.

Capability mirror of the reference's `StandardAutoscaler`
(`python/ray/autoscaler/_private/autoscaler.py:166,357` — read load →
bin-pack demands → `NodeProvider` launch/terminate) with the TPU twist
that node types describe slices (a provider node = a TPU host with its
chips).  `LocalNodeProvider` boots real nodelet processes, so scaling
behavior is testable on one machine (the reference's fake_multi_node
strategy).
"""

from .autoscaler import StandardAutoscaler, request_resources  # noqa: F401
from .aws_provider import AwsProvider  # noqa: F401
from .azure_provider import AzureProvider  # noqa: F401
from .gce_provider import GceProvider  # noqa: F401
from .kuberay_provider import KubeRayProvider  # noqa: F401
from .node_provider import LocalNodeProvider, NodeProvider  # noqa: F401
from .tpu_pod_provider import TpuPodProvider  # noqa: F401
