"""TPU-pod node provider: scale the cluster with Cloud TPU slices.

Capability mirror of the reference's cloud providers
(/root/reference/python/ray/autoscaler/_private/gcp/node_provider.py and
the provider plugin registry, `python/ray/autoscaler/node_provider.py`) —
specialized for TPU pods: a "node" is a whole TPU slice (queued resource /
tpu-vm), every host of which runs a nodelet that joins the cluster, so one
scale-up decision brings an ICI-connected sub-mesh online (bundles →
contiguous slices, the SURVEY §2.4 placement row).

All cloud mutations go through the ``gcloud`` CLI (subprocess) rather than
a vendored SDK: zero extra dependencies, and unit tests inject a fake
runner.  Startup wiring: each created slice boots with a startup script
that launches ``ray-tpu start --address <head>`` on every host.
"""

from __future__ import annotations

import json
import shlex
import subprocess
from typing import Any, Callable, Dict, List, Optional

from .node_provider import NodeProvider

# accelerator-type -> per-host resources (chips per host on v4/v5 pods)
_DEFAULT_HOST_RESOURCES = {"CPU": 8.0, "TPU": 4.0}


def _run_gcloud(args: List[str], timeout: float = 120.0) -> str:
    out = subprocess.run(["gcloud"] + args, capture_output=True, text=True,
                         timeout=timeout)
    if out.returncode != 0:
        raise RuntimeError(f"gcloud {' '.join(args)} failed: "
                           f"{out.stderr.strip()[-500:]}")
    return out.stdout


class TpuPodProvider(NodeProvider):
    """Provision/terminate TPU slices via ``gcloud compute tpus tpu-vm``.

    node_types maps a logical name to the slice shape, e.g.::

        {"v4_8": {"accelerator_type": "v4-8", "runtime_version":
                  "tpu-ubuntu2204-base", "hosts": 1},
         "v4_32": {"accelerator_type": "v4-32", "hosts": 4}}
    """

    def __init__(self, *, project: str, zone: str, head_address: str,
                 node_types: Dict[str, Dict[str, Any]],
                 name_prefix: str = "ray-tpu",
                 runner: Optional[Callable[[List[str]], str]] = None):
        self.project = project
        self.zone = zone
        self.head_address = head_address
        self.node_types = node_types
        self.name_prefix = name_prefix
        self._run = runner or _run_gcloud
        self._seq = 0

    # -- provider contract ---------------------------------------------------
    def node_resources(self, node_type: str) -> Dict[str, float]:
        nt = self.node_types[node_type]
        hosts = int(nt.get("hosts", 1))
        per_host = dict(nt.get("host_resources", _DEFAULT_HOST_RESOURCES))
        # the scheduler sees one "node" per host; a slice contributes
        # hosts × per-host resources toward demand satisfaction
        return {k: v * hosts for k, v in per_host.items()}

    def create_node(self, node_type: str) -> str:
        nt = self.node_types[node_type]
        self._seq += 1
        name = f"{self.name_prefix}-{node_type}-{self._seq}".replace(
            "_", "-")
        startup = self._startup_script(nt)
        self._run([
            "compute", "tpus", "tpu-vm", "create", name,
            "--project", self.project, "--zone", self.zone,
            "--accelerator-type", nt["accelerator_type"],
            "--version", nt.get("runtime_version",
                                "tpu-ubuntu2204-base"),
            # ^|@|^ sets a custom list delimiter: gcloud otherwise splits
            # --metadata on COMMAS, truncating any script that
            # contains one
            "--metadata", f"^|@|^startup-script={startup}",
        ], timeout=600.0)
        return name

    def terminate_node(self, provider_node_id: str) -> None:
        self._run([
            "compute", "tpus", "tpu-vm", "delete", provider_node_id,
            "--project", self.project, "--zone", self.zone, "--quiet",
        ], timeout=600.0)

    def non_terminated_nodes(self) -> List[str]:
        out = self._run([
            "compute", "tpus", "tpu-vm", "list",
            "--project", self.project, "--zone", self.zone,
            "--format", "json",
        ])
        nodes = json.loads(out or "[]")
        return [n["name"].rsplit("/", 1)[-1] for n in nodes
                if n["name"].rsplit("/", 1)[-1].startswith(
                    self.name_prefix)
                and n.get("state") in ("READY", "CREATING", None)]

    # -- wiring ---------------------------------------------------------------
    def _startup_script(self, nt: Dict[str, Any]) -> str:
        """Every host of the slice joins the cluster as a nodelet; the
        TPU chips autodetect (`detect_tpu_resources`), so the scheduler
        sees `TPU` + `accelerator_type:<gen>` on each host."""
        extra = nt.get("setup_commands", [])
        join = (f"ray-tpu start --address "
                f"{shlex.quote(self.head_address)}")
        return "#! /bin/bash\n" + "\n".join([*extra, join]) + "\n"
