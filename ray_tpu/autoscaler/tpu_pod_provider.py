"""TPU-pod node provider: scale the cluster with Cloud TPU slices.

Capability mirror of the reference's cloud providers
(/root/reference/python/ray/autoscaler/_private/gcp/node_provider.py and
the provider plugin registry, `python/ray/autoscaler/node_provider.py`) —
specialized for TPU pods: a "node" is a whole TPU slice (queued resource /
tpu-vm), every host of which runs a nodelet that joins the cluster, so one
scale-up decision brings an ICI-connected sub-mesh online (bundles →
contiguous slices, the SURVEY §2.4 placement row).

All cloud mutations go through the ``gcloud`` CLI (subprocess) rather than
a vendored SDK: zero extra dependencies, and unit tests inject a fake
runner.  Startup wiring: each created slice boots with a startup script
that launches ``ray-tpu start --address <head>`` on every host.
"""

from __future__ import annotations

import json
import os
import shlex
import subprocess
import threading
from typing import Any, Callable, Dict, List, Optional

from .node_provider import NodeProvider

# accelerator-type -> per-host resources (chips per host on v4/v5 pods)
_DEFAULT_HOST_RESOURCES = {"CPU": 8.0, "TPU": 4.0}


def _run_gcloud(args: List[str], timeout: float = 120.0) -> str:
    out = subprocess.run(["gcloud"] + args, capture_output=True, text=True,
                         timeout=timeout)
    if out.returncode != 0:
        raise RuntimeError(f"gcloud {' '.join(args)} failed: "
                           f"{out.stderr.strip()[-500:]}")
    return out.stdout


class TpuPodProvider(NodeProvider):
    """Provision/terminate TPU slices via ``gcloud compute tpus tpu-vm``.

    node_types maps a logical name to the slice shape, e.g.::

        {"v4_8": {"accelerator_type": "v4-8", "runtime_version":
                  "tpu-ubuntu2204-base", "hosts": 1},
         "v4_32": {"accelerator_type": "v4-32", "hosts": 4}}
    """

    def __init__(self, *, project: str, zone: str, head_address: str,
                 node_types: Dict[str, Dict[str, Any]],
                 name_prefix: str = "ray-tpu",
                 runner: Optional[Callable[[List[str]], str]] = None):
        self.project = project
        self.zone = zone
        self.head_address = head_address
        self.node_types = node_types
        self.name_prefix = name_prefix
        self._run = runner or _run_gcloud
        self._seq = 0

    # -- provider contract ---------------------------------------------------
    def node_resources(self, node_type: str) -> Dict[str, float]:
        nt = self.node_types[node_type]
        hosts = int(nt.get("hosts", 1))
        per_host = dict(nt.get("host_resources", _DEFAULT_HOST_RESOURCES))
        # the scheduler sees one "node" per host; a slice contributes
        # hosts × per-host resources toward demand satisfaction
        return {k: v * hosts for k, v in per_host.items()}

    def create_node(self, node_type: str) -> str:
        nt = self.node_types[node_type]
        self._seq += 1
        name = f"{self.name_prefix}-{node_type}-{self._seq}".replace(
            "_", "-")
        startup = self._startup_script(nt)
        self._run([
            "compute", "tpus", "tpu-vm", "create", name,
            "--project", self.project, "--zone", self.zone,
            "--accelerator-type", nt["accelerator_type"],
            "--version", nt.get("runtime_version",
                                "tpu-ubuntu2204-base"),
            # ^|@|^ sets a custom list delimiter: gcloud otherwise splits
            # --metadata on COMMAS, truncating any script that
            # contains one
            "--metadata", f"^|@|^startup-script={startup}",
        ], timeout=600.0)
        return name

    def terminate_node(self, provider_node_id: str) -> None:
        self._run([
            "compute", "tpus", "tpu-vm", "delete", provider_node_id,
            "--project", self.project, "--zone", self.zone, "--quiet",
        ], timeout=600.0)

    def non_terminated_nodes(self) -> List[str]:
        out = self._run([
            "compute", "tpus", "tpu-vm", "list",
            "--project", self.project, "--zone", self.zone,
            "--format", "json",
        ])
        nodes = json.loads(out or "[]")
        return [n["name"].rsplit("/", 1)[-1] for n in nodes
                if n["name"].rsplit("/", 1)[-1].startswith(
                    self.name_prefix)
                and n.get("state") in ("READY", "CREATING", None)]

    # -- maintenance notices --------------------------------------------------
    def maintenance_notices(self) -> List[Dict[str, Any]]:
        """Upcoming-maintenance notices for our slices.  Cloud TPU
        announces host maintenance through the VM metadata server /
        `upcoming-maintenance` and queued-resource state; here we read
        the slice descriptions and surface any with a scheduled event.
        Tests (and air-gapped runs) inject notices via
        ``RAY_TPU_MAINT_NOTICE_FILE`` instead (MaintenanceWatcher)."""
        out = self._run([
            "compute", "tpus", "tpu-vm", "list",
            "--project", self.project, "--zone", self.zone,
            "--format", "json",
        ])
        notices = []
        for n in json.loads(out or "[]"):
            name = n.get("name", "").rsplit("/", 1)[-1]
            if not name.startswith(self.name_prefix):
                continue
            window = (n.get("scheduling") or {}).get("upcomingMaintenance") \
                or n.get("upcomingMaintenance")
            if window:
                notices.append({"host": name, "window": window})
        return notices

    # -- wiring ---------------------------------------------------------------
    def _startup_script(self, nt: Dict[str, Any]) -> str:
        """Every host of the slice joins the cluster as a nodelet; the
        TPU chips autodetect (`detect_tpu_resources`), so the scheduler
        sees `TPU` + `accelerator_type:<gen>` on each host."""
        extra = nt.get("setup_commands", [])
        join = (f"ray-tpu start --address "
                f"{shlex.quote(self.head_address)}")
        return "#! /bin/bash\n" + "\n".join([*extra, join]) + "\n"


class MaintenanceWatcher:
    """Turns announced TPU departures into graceful drains.

    Polls a notice source and issues ``drain_node`` to the controller
    for every affected node — so a maintenance event or preemption with
    60 s of warning becomes a phased evacuation instead of a crash the
    lineage machinery has to mop up.

    Notice source (injectable): ``fetch_notices()`` returns a list of
    dicts, each naming a node by ``node_id`` (controller hex id) or by
    ``host`` (matched against the node's address or hostname label).
    The default source reads a JSON file named by
    ``RAY_TPU_MAINT_NOTICE_FILE`` — the hook both tests and external
    notice daemons (metadata-server watchers) use; a provider's
    ``maintenance_notices`` can be passed directly as the fetcher."""

    def __init__(self, controller_addr: str,
                 fetch_notices: Optional[Callable[[], List[Dict[str, Any]]]]
                 = None,
                 drain_fn: Optional[Callable[[str, Optional[float]], Any]]
                 = None,
                 drain_timeout_s: Optional[float] = None):
        self.controller_addr = controller_addr
        self._fetch = fetch_notices or self._fetch_from_file
        self._drain = drain_fn or self._drain_via_controller
        self.drain_timeout_s = drain_timeout_s
        self._drained: set = set()     # node ids already handed a drain
        self._client = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- notice sources -------------------------------------------------------
    @staticmethod
    def _fetch_from_file() -> List[Dict[str, Any]]:
        path = os.environ.get("RAY_TPU_MAINT_NOTICE_FILE")
        if not path or not os.path.exists(path):
            return []
        try:
            with open(path) as f:
                notices = json.load(f)
            return list(notices) if isinstance(notices, list) else []
        except (OSError, ValueError):
            return []

    # -- controller plumbing --------------------------------------------------
    def _conn(self):
        if self._client is None:
            from ..core import rpc
            lt = rpc.EventLoopThread("maint-watcher-io")
            self._client = rpc.BlockingClient.connect_ha(
                lt, self.controller_addr, retries=10)
        return self._client

    def _list_nodes(self) -> List[Dict[str, Any]]:
        return self._conn().call("list_nodes", {}, timeout=10)

    def _drain_via_controller(self, node_id: str,
                              timeout_s: Optional[float]):
        budget = timeout_s or 600.0
        return self._conn().call(
            "drain_node", {"node_id": node_id, "timeout_s": timeout_s,
                           "wait": False}, timeout=budget + 30)

    def _resolve(self, notice: Dict[str, Any]) -> Optional[str]:
        nid = notice.get("node_id")
        if nid:
            return nid
        host = notice.get("host")
        if not host:
            return None
        for n in self._list_nodes():
            if not n.get("alive"):
                continue
            if n.get("addr", "").split(":")[0] == host \
                    or (n.get("labels") or {}).get("hostname") == host:
                return n["id"]
        return None

    # -- the watch loop -------------------------------------------------------
    def poll_once(self) -> List[str]:
        """One notice sweep; returns the node ids newly handed a drain."""
        drained = []
        for notice in self._fetch():
            try:
                node_id = self._resolve(notice)
            except Exception:
                continue
            if node_id is None or node_id in self._drained:
                continue
            timeout = notice.get("timeout_s", self.drain_timeout_s)
            try:
                self._drain(node_id, timeout)
            except Exception:
                continue  # controller unreachable: retry next poll
            self._drained.add(node_id)
            drained.append(node_id)
        return drained

    def start(self, interval_s: Optional[float] = None) -> None:
        from ..core.config import GlobalConfig
        period = interval_s or GlobalConfig.maintenance_poll_interval_s

        def loop():
            while not self._stop.wait(period):
                try:
                    self.poll_once()
                except Exception:
                    pass  # the watcher must never die

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="maintenance-watcher")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
