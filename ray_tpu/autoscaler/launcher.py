"""Cluster launcher: `ray-tpu up / down / exec` from a YAML config.

Capability mirror of the reference's cluster launcher
(`python/ray/scripts/scripts.py:529` up / `:974` down / `:1161` attach /
exec; YAML schema `python/ray/autoscaler/ray-schema.json`): a config file
names a provider and worker node types, `up` boots the head (controller +
nodelet) and the initial workers through the provider, `down` terminates
everything, `exec` runs a command against the live cluster.  Providers:

* ``local`` — worker nodelets as processes on this machine (the
  fake-multi-node story; full control-plane fidelity, no cloud).
* ``tpu_pod`` — TPU slices via ``gcloud`` (autoscaler/tpu_pod_provider).

Example config::

    cluster_name: dev
    provider:
      type: local
    head:
      num_cpus: 4
    workers:
      cpu_worker:
        count: 2
        resources: {CPU: 2}

Cluster state persists under ``~/.ray_tpu/clusters/<name>.json`` so
``down``/``exec`` find the running processes across CLI invocations.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
from typing import Any, Dict, List, Optional

import yaml

def _state_dir() -> str:
    # overridable so tests (and parallel CI runs) get isolated state
    return os.environ.get("RAY_TPU_CLUSTER_STATE_DIR",
                          os.path.expanduser("~/.ray_tpu/clusters"))


def _state_path(name: str) -> str:
    d = _state_dir()
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f"{name}.json")


def load_config(path: str) -> Dict[str, Any]:
    with open(path) as f:
        cfg = yaml.safe_load(f) or {}
    if "cluster_name" not in cfg:
        raise ValueError("cluster config needs a cluster_name")
    provider = (cfg.get("provider") or {}).get("type", "local")
    if provider not in _PROVIDER_TYPES:
        raise ValueError(f"unknown provider type {provider!r} "
                         f"(supported: {_PROVIDER_TYPES})")
    return cfg


def up(config_path: str) -> Dict[str, Any]:
    """Boot the head + initial workers; returns the cluster state."""
    from ..core import node as node_mod

    cfg = load_config(config_path)
    name = cfg["cluster_name"]
    state_file = _state_path(name)
    if os.path.exists(state_file):
        raise RuntimeError(
            f"cluster {name!r} appears to be running "
            f"({state_file} exists); `down` it first")

    session_dir = node_mod.new_session_dir()
    head_cfg = cfg.get("head") or {}
    controller_proc, controller_addr = node_mod.start_controller(session_dir)
    try:
        resources = {"CPU": float(head_cfg.get("num_cpus", 4))}
        if head_cfg.get("num_tpus"):
            resources["TPU"] = float(head_cfg["num_tpus"])
        nodelet_proc, nodelet_addr, node_id, _ = node_mod.start_nodelet(
            session_dir, controller_addr, resources,
            int(head_cfg.get("object_store_memory", 0)))
    except BaseException:
        # no state file exists yet: kill the detached controller here or
        # nothing ever will
        try:
            controller_proc.kill()
        except Exception:
            pass
        raise

    state: Dict[str, Any] = {
        "cluster_name": name,
        "config_path": os.path.abspath(config_path),
        "controller": controller_addr,
        "nodelet": nodelet_addr,
        "session_dir": session_dir,
        "pids": [controller_proc.proc.pid, nodelet_proc.proc.pid],
        "provider": (cfg.get("provider") or {}).get("type", "local"),
        "provider_nodes": [],
    }

    # persist as soon as the head is up: if worker bring-up fails below,
    # `down` can still find and terminate everything started so far
    with open(state_file, "w") as f:
        json.dump(state, f, indent=2)

    try:
        provider = _make_provider(cfg, session_dir, controller_addr)
        for wtype, wcfg in (cfg.get("workers") or {}).items():
            count = int((wcfg or {}).get("count", 0))
            shape = {k: v for k, v in (wcfg or {}).items()
                     if k != "count"}
            # explicit provider contract: each provider decides what a
            # YAML worker shape means to it (KubeRay: nothing — the CR
            # is its source of truth)
            provider.set_node_type(wtype, shape)
            for _ in range(count):
                nid = provider.create_node(wtype)
                state["provider_nodes"].append(nid)
                entry = getattr(provider, "_nodes", {}).get(nid)
                proc = getattr(entry[0], "proc", None) if entry else None
                if proc is not None:
                    state["pids"].append(proc.pid)
                with open(state_file, "w") as f:
                    json.dump(state, f, indent=2)
    except BaseException:
        try:
            down(name)
        except Exception:
            pass
        raise
    return state


def down(name_or_config: str) -> Dict[str, Any]:
    """Terminate every process/instance of the named cluster."""
    name = _resolve_name(name_or_config)
    state_file = _state_path(name)
    if not os.path.exists(state_file):
        raise RuntimeError(f"no running cluster named {name!r}")
    with open(state_file) as f:
        state = json.load(f)
    if state.get("provider") not in (None, "local"):
        # EVERY cloud provider's nodes must terminate here (local
        # workers are plain pids handled below).  Best effort: a
        # moved/deleted YAML must not make the cluster permanently
        # un-down-able — the head pids and the state file still get
        # cleaned up below either way
        try:
            cfg = load_config(state["config_path"])
            provider = _make_provider(cfg, state["session_dir"],
                                      state["controller"])
            for nid in state.get("provider_nodes", []):
                try:
                    provider.terminate_node(nid)
                except Exception:
                    pass
        except Exception as e:
            import sys as _sys
            print(f"ray_tpu: could not terminate provider nodes "
                  f"{state.get('provider_nodes')} "
                  f"({type(e).__name__}: {e}); clean these up via the "
                  "cloud console", file=_sys.stderr)
    for pid in reversed(state.get("pids", [])):  # workers before head
        try:
            os.kill(pid, signal.SIGTERM)
        except OSError:
            pass
    # reap any that are OUR children (an in-process `up` leaves them as
    # zombies otherwise; cross-process `down` gets ECHILD, fine) —
    # bounded retry, since they need a moment to exit after SIGTERM
    import time as _time
    pending = list(state.get("pids", []))
    deadline = _time.monotonic() + 5.0
    while pending and _time.monotonic() < deadline:
        still = []
        for pid in pending:
            try:
                done_pid, _ = os.waitpid(pid, os.WNOHANG)
                if done_pid == 0:
                    still.append(pid)
            except OSError:
                pass  # not our child / already reaped
        pending = still
        if pending:
            _time.sleep(0.1)
    os.unlink(state_file)
    return state


def exec_cmd(name_or_config: str, command,
             timeout: Optional[float] = None) -> int:
    """Run a command with the cluster's address exported (the local-form
    `ray exec`: the command lands on the head environment).

    A string runs through the shell like the reference's `ray exec`;
    a list runs as an exact argv (programmatic callers keep precise
    semantics — the CLI decides which form a user's input is)."""
    name = _resolve_name(name_or_config)
    with open(_state_path(name)) as f:
        state = json.load(f)
    env = dict(os.environ)
    env["RAY_TPU_ADDRESS"] = state["controller"]
    env["RAY_TPU_NODELET"] = state["nodelet"]
    env["RAY_TPU_SESSION_DIR"] = state["session_dir"]
    proc = subprocess.run(command, env=env, timeout=timeout,
                          shell=isinstance(command, str))
    return proc.returncode


def get_state(name_or_config: str) -> Optional[Dict[str, Any]]:
    name = _resolve_name(name_or_config)
    path = _state_path(name)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def _resolve_name(name_or_config: str) -> str:
    if os.path.exists(name_or_config) and \
            name_or_config.endswith((".yaml", ".yml")):
        return load_config(name_or_config)["cluster_name"]
    return name_or_config


_PROVIDER_TYPES = ("local", "tpu_pod", "gce", "aws", "kuberay")


def _make_provider(cfg: Dict[str, Any], session_dir: str,
                   controller_addr: str):
    from .node_provider import LocalNodeProvider
    ptype = (cfg.get("provider") or {}).get("type", "local")
    if ptype == "local":
        return LocalNodeProvider(session_dir, controller_addr,
                                 node_types={})
    p = dict(cfg["provider"])
    p.pop("type")
    if ptype == "tpu_pod":
        from .tpu_pod_provider import TpuPodProvider
        return TpuPodProvider(head_address=controller_addr,
                              node_types={}, **p)
    if ptype == "gce":
        from .gce_provider import GceProvider
        return GceProvider(head_address=controller_addr,
                           node_types={}, **p)
    if ptype == "aws":
        from .aws_provider import AwsProvider
        p.setdefault("cluster_name", cfg["cluster_name"])
        return AwsProvider(head_address=controller_addr,
                           node_types={}, **p)
    if ptype == "kuberay":
        from .kuberay_provider import KubeRayProvider
        p.setdefault("cluster_name", cfg["cluster_name"])
        return KubeRayProvider(**p)
    raise ValueError(f"unknown provider type {ptype!r}")
