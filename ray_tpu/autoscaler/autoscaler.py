"""The reconciliation loop.

Capability mirror of the reference's `StandardAutoscaler.update`
(`autoscaler.py:166,357`) + `ResourceDemandScheduler.get_nodes_to_launch`
(`resource_demand_scheduler.py:103,171`): demands (explicit
`request_resources` bundles + unplaceable-shortfall heuristics) bin-pack
onto node types; idle nodes terminate after a timeout.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from .node_provider import NodeProvider

_pending_requests: List[Dict[str, float]] = []


def request_resources(bundles: List[Dict[str, float]]) -> None:
    """Explicit demand hint (reference:
    `ray.autoscaler.sdk.request_resources`)."""
    _pending_requests.clear()
    _pending_requests.extend(dict(b) for b in bundles)


class StandardAutoscaler:
    def __init__(self, provider: NodeProvider, *,
                 max_workers: int = 8,
                 idle_timeout_s: float = 30.0,
                 upscale_headroom: float = 0.0,
                 state_source=None):
        """``state_source``: callable returning the node table (defaults to
        `ray_tpu.state.list_nodes` on the connected cluster)."""
        self.provider = provider
        self.max_workers = max_workers
        self.idle_timeout_s = idle_timeout_s
        self.upscale_headroom = upscale_headroom
        self._idle_since: Dict[str, float] = {}
        self._state_source = state_source

    def _nodes(self) -> List[Dict[str, Any]]:
        if self._state_source is not None:
            return self._state_source()
        from .. import state
        return state.list_nodes()

    @staticmethod
    def _fits(bundle: Dict[str, float],
              avail: Dict[str, float]) -> bool:
        return all(avail.get(k, 0.0) >= v for k, v in bundle.items())

    def _nodes_to_launch(self, alive: List[Dict[str, Any]]
                         ) -> Dict[str, int]:
        """Bin-pack outstanding demand bundles onto existing free capacity;
        whatever doesn't fit maps to new nodes by type.  Demand =
        explicit `request_resources` bundles + the waiting lease
        requests every nodelet reports in its heartbeat (the reference's
        ResourceDemandScheduler load signal) — so queued-but-unplaceable
        tasks drive scale-up without any user hint."""
        free = [dict(n.get("avail", {})) for n in alive]
        launch: Dict[str, int] = {}
        # Provider nodes LAUNCHED but not yet alive in the cluster view
        # are capacity in flight: count them, or the same demand bundle
        # re-launches a node every tick until the first one boots (real
        # VMs take minutes) and the fleet balloons to max_workers.
        alive_ids = {n.get("id") for n in alive}
        pending_caps: List[Dict[str, float]] = []
        for pid in self.provider.non_terminated_nodes():
            if pid in alive_ids:
                continue
            ntype = getattr(self.provider, "node_type_of",
                            lambda _: None)(pid)
            if ntype is not None:
                pending_caps.append(self.provider.node_resources(ntype))
        reported = [dict(b) for n in alive
                    for b in (n.get("demand") or [])]
        for bundle in list(_pending_requests) + reported:
            placed = False
            for cap in free + pending_caps:
                if self._fits(bundle, cap):
                    for k, v in bundle.items():
                        cap[k] = cap.get(k, 0.0) - v
                    placed = True
                    break
            if placed:
                continue
            # need a new node: first type that can hold the bundle
            for ntype in self.provider.node_types:
                cap = self.provider.node_resources(ntype)
                if self._fits(bundle, cap):
                    for k, v in bundle.items():
                        cap[k] -= v
                    pending_caps.append(cap)
                    launch[ntype] = launch.get(ntype, 0) + 1
                    break
        return launch

    def update(self) -> Dict[str, Any]:
        """One reconciliation step; returns a summary of actions."""
        nodes = self._nodes()
        alive = [n for n in nodes if n.get("alive")]
        actions = {"launched": [], "terminated": []}

        current_workers = len(self.provider.non_terminated_nodes())
        for ntype, count in self._nodes_to_launch(alive).items():
            for _ in range(count):
                if current_workers >= self.max_workers:
                    break
                actions["launched"].append(
                    self.provider.create_node(ntype))
                current_workers += 1
        if actions["launched"]:
            _pending_requests.clear()

        # idle downscaling: a provider node whose avail == total for longer
        # than idle_timeout_s terminates
        now = time.monotonic()
        provider_ids = set(self.provider.non_terminated_nodes())
        for n in alive:
            nid = n.get("id")
            if nid not in provider_ids:
                continue  # not ours (e.g. the head node)
            idle = n.get("avail") == n.get("total")
            if not idle:
                self._idle_since.pop(nid, None)
                continue
            first = self._idle_since.setdefault(nid, now)
            if now - first >= self.idle_timeout_s:
                self.provider.terminate_node(nid)
                actions["terminated"].append(nid)
                self._idle_since.pop(nid, None)
        return actions

    def run(self, interval_s: float = 5.0, stop_event=None) -> None:
        """The monitor loop (reference: `monitor.py:126`)."""
        while stop_event is None or not stop_event.is_set():
            self.update()
            time.sleep(interval_s)
