"""Node providers (reference: `python/ray/autoscaler/node_provider.py` +
`_private/fake_multi_node/node_provider.py`)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional


class NodeProvider:
    """Minimal provider contract: launch/terminate/list."""

    def set_node_type(self, name: str, shape: Dict[str, Any]) -> None:
        """Register a worker shape from cluster YAML (`ray-tpu up`).
        Providers whose shapes live elsewhere (KubeRay reads the
        RayCluster CR) override this to a no-op."""
        self.node_types[name] = shape    # type: ignore[attr-defined]

    def create_node(self, node_type: str) -> str:
        raise NotImplementedError

    def terminate_node(self, provider_node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError

    def node_resources(self, node_type: str) -> Dict[str, float]:
        raise NotImplementedError


class LocalNodeProvider(NodeProvider):
    """Spawns real nodelet processes on this machine (the fake-multi-node
    equivalent): scaling tests exercise the actual control plane."""

    def __init__(self, session_dir: str, controller_addr: str,
                 node_types: Optional[Dict[str, Dict[str, float]]] = None,
                 object_store_memory: int = 64 * 1024 * 1024):
        self.session_dir = session_dir
        self.controller_addr = controller_addr
        self.node_types = node_types or {
            "cpu_worker": {"CPU": 2.0},
        }
        self.object_store_memory = object_store_memory
        self._nodes: Dict[str, Any] = {}

    def set_node_type(self, name: str, shape: Dict[str, Any]) -> None:
        # local workers are plain processes: only the resource bag
        # matters out of the YAML shape
        self.node_types[name] = dict(shape.get("resources")
                                     or {"CPU": 2.0})

    def node_resources(self, node_type: str) -> Dict[str, float]:
        return dict(self.node_types[node_type])

    def create_node(self, node_type: str) -> str:
        from ..core import node as node_mod
        handle, addr, node_id, store = node_mod.start_nodelet(
            self.session_dir, self.controller_addr,
            self.node_resources(node_type), self.object_store_memory)
        self._nodes[node_id] = (handle, store, node_type)
        return node_id

    def terminate_node(self, provider_node_id: str) -> None:
        entry = self._nodes.pop(provider_node_id, None)
        if entry is None:
            return
        handle, store, _ = entry
        try:
            handle.kill()
        except Exception:
            pass
        import os
        try:
            os.unlink(store)
        except OSError:
            pass

    def non_terminated_nodes(self) -> List[str]:
        return [nid for nid, (h, _, _) in self._nodes.items() if h.alive()]

    def node_type_of(self, node_id: str) -> Optional[str]:
        entry = self._nodes.get(node_id)
        return entry[2] if entry else None
