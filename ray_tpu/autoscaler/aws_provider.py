"""AWS EC2 node provider: scale with EC2 instances.

Capability mirror of the reference's AWS provider
(/root/reference/python/ray/autoscaler/_private/aws/node_provider.py:97
— boto3 run/terminate/describe with cluster+type tags and user-data
bootstrap).  The boto3 client is INJECTED (any object with the
run_instances/terminate_instances/describe_instances surface works), so
the provider is contract-testable with recorded-response fakes on an
image that ships no cloud SDKs; at runtime the default constructor
builds the real client lazily.
"""

from __future__ import annotations

import shlex
from typing import Any, Callable, Dict, List, Optional

from .node_provider import NodeProvider

_DEFAULT_RESOURCES = {"CPU": 4.0}
#: tag keys (reference: autoscaler/tags.py TAG_RAY_CLUSTER_NAME etc.)
TAG_CLUSTER = "ray-tpu-cluster"
TAG_NODE_TYPE = "ray-tpu-node-type"


def _default_ec2(region: str):
    try:
        import boto3
    except ImportError as exc:
        raise RuntimeError(
            "AwsProvider needs boto3 at runtime (not shipped in this "
            "image) — or inject ec2= with a client-shaped object"
        ) from exc
    return boto3.client("ec2", region_name=region)


class AwsProvider(NodeProvider):
    """Provision/terminate EC2 worker instances.

    node_types maps a logical name onto the instance shape::

        {"cpu_16": {"instance_type": "m6i.4xlarge",
                    "ami": "ami-...",
                    "host_resources": {"CPU": 16},
                    "subnet_id": "subnet-...",        # optional
                    "key_name": "...",                # optional
                    "setup_commands": ["pip install ..."]}}
    """

    def __init__(self, *, region: str, head_address: str,
                 cluster_name: str,
                 node_types: Dict[str, Dict[str, Any]],
                 ec2: Optional[Any] = None):
        self.region = region
        self.head_address = head_address
        self.cluster_name = cluster_name
        self.node_types = node_types
        self._ec2 = ec2 if ec2 is not None else _default_ec2(region)
        self._type_by_id: Dict[str, str] = {}

    # -- provider contract ---------------------------------------------------
    def node_resources(self, node_type: str) -> Dict[str, float]:
        nt = self.node_types[node_type]
        return dict(nt.get("host_resources", _DEFAULT_RESOURCES))

    def create_node(self, node_type: str) -> str:
        nt = self.node_types[node_type]
        user_data = self._user_data(nt)
        resp = self._ec2.run_instances(
            ImageId=nt["ami"],
            InstanceType=nt.get("instance_type", "m6i.xlarge"),
            MinCount=1, MaxCount=1,
            # RAW script: boto3 base64-encodes UserData itself —
            # pre-encoding would hand cloud-init a double-encoded blob
            UserData=user_data,
            TagSpecifications=[{
                "ResourceType": "instance",
                "Tags": [
                    {"Key": TAG_CLUSTER, "Value": self.cluster_name},
                    {"Key": TAG_NODE_TYPE, "Value": node_type},
                    {"Key": "Name",
                     "Value": f"ray-tpu-{self.cluster_name}-"
                              f"{node_type}"},
                ],
            }],
            **({"SubnetId": nt["subnet_id"]} if nt.get("subnet_id")
               else {}),
            **({"KeyName": nt["key_name"]} if nt.get("key_name")
               else {}),
        )
        iid = resp["Instances"][0]["InstanceId"]
        self._type_by_id[iid] = node_type
        return iid

    def terminate_node(self, provider_node_id: str) -> None:
        self._ec2.terminate_instances(InstanceIds=[provider_node_id])
        self._type_by_id.pop(provider_node_id, None)

    def non_terminated_nodes(self) -> List[str]:
        resp = self._ec2.describe_instances(Filters=[
            {"Name": f"tag:{TAG_CLUSTER}",
             "Values": [self.cluster_name]},
            {"Name": "instance-state-name",
             "Values": ["pending", "running"]},
        ])
        ids = []
        for res in resp.get("Reservations", []):
            for inst in res.get("Instances", []):
                ids.append(inst["InstanceId"])
                # rebuild the type map across provider restarts from
                # the instance tags (the reference does the same)
                for tag in inst.get("Tags", []):
                    if tag["Key"] == TAG_NODE_TYPE:
                        self._type_by_id[inst["InstanceId"]] = \
                            tag["Value"]
        return ids

    def node_type_of(self, node_id: str) -> Optional[str]:
        return self._type_by_id.get(node_id)

    # -- wiring ---------------------------------------------------------------
    def _user_data(self, nt: Dict[str, Any]) -> str:
        res = dict(nt.get("host_resources", _DEFAULT_RESOURCES))
        extra = nt.get("setup_commands", [])
        join = (f"ray-tpu start --address "
                f"{shlex.quote(self.head_address)} "
                f"--num-cpus {int(res.get('CPU', 4))}")
        return "#!/bin/bash\n" + "\n".join([*extra, join]) + "\n"
