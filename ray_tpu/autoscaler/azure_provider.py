"""Azure VM node provider: scale with Azure virtual machines.

Capability mirror of the reference's Azure provider
(/root/reference/python/ray/autoscaler/_private/_azure/node_provider.py:42
— azure-mgmt-compute create/delete/list with cluster+type tags and a
custom-data bootstrap script).  Like aws_provider.py, the management
client is INJECTED (any object with the begin_create_or_update /
begin_delete / list surface works), so the provider is contract-testable
with recorded-response fakes on an image that ships no cloud SDKs; at
runtime the default constructor builds the real client lazily.
"""

from __future__ import annotations

import base64
import shlex
import uuid
from typing import Any, Dict, List, Optional

from .node_provider import NodeProvider

_DEFAULT_RESOURCES = {"CPU": 4.0}
TAG_CLUSTER = "ray-tpu-cluster"
TAG_NODE_TYPE = "ray-tpu-node-type"


def _default_compute(subscription_id: str):
    try:
        from azure.identity import DefaultAzureCredential
        from azure.mgmt.compute import ComputeManagementClient
    except ImportError as exc:
        raise RuntimeError(
            "AzureProvider needs azure-mgmt-compute + azure-identity at "
            "runtime (not shipped in this image) — or inject compute= "
            "with a client-shaped object") from exc
    return ComputeManagementClient(DefaultAzureCredential(),
                                   subscription_id)


class AzureProvider(NodeProvider):
    """Provision/terminate Azure VM workers.

    node_types maps a logical name onto the VM shape::

        {"cpu_16": {"vm_size": "Standard_D16s_v5",
                    "image_id": "/subscriptions/.../images/...",
                    "host_resources": {"CPU": 16},
                    "admin_username": "ray",          # optional
                    "ssh_public_key": "ssh-rsa ...",  # optional
                    "setup_commands": ["pip install ..."]}}

    VM/NIC plumbing beyond the shape (vnet, subnet) is expected to be
    baked into the image/template like the reference's deployment
    template (`_azure/azure-vm-template.json`).
    """

    def __init__(self, *, subscription_id: str, resource_group: str,
                 location: str, head_address: str, cluster_name: str,
                 node_types: Dict[str, Dict[str, Any]],
                 compute: Optional[Any] = None):
        self.subscription_id = subscription_id
        self.resource_group = resource_group
        self.location = location
        self.head_address = head_address
        self.cluster_name = cluster_name
        self.node_types = node_types
        self._compute = compute if compute is not None \
            else _default_compute(subscription_id)
        self._type_by_id: Dict[str, str] = {}

    # -- provider contract ---------------------------------------------------
    def node_resources(self, node_type: str) -> Dict[str, float]:
        nt = self.node_types[node_type]
        return dict(nt.get("host_resources", _DEFAULT_RESOURCES))

    def create_node(self, node_type: str) -> str:
        nt = self.node_types[node_type]
        vm_name = f"ray-tpu-{self.cluster_name}-{node_type}-" \
                  f"{uuid.uuid4().hex[:8]}"
        custom_data = base64.b64encode(
            self._bootstrap(nt).encode()).decode()
        params = {
            "location": self.location,
            "tags": {TAG_CLUSTER: self.cluster_name,
                     TAG_NODE_TYPE: node_type},
            "hardware_profile": {
                "vm_size": nt.get("vm_size", "Standard_D4s_v5")},
            "storage_profile": {
                "image_reference": {"id": nt["image_id"]}},
            # Azure delivers custom data base64-encoded to cloud-init
            "os_profile": {
                "computer_name": vm_name,
                "admin_username": nt.get("admin_username", "ray"),
                "custom_data": custom_data,
                **({"linux_configuration": {
                    "disable_password_authentication": True,
                    "ssh": {"public_keys": [{
                        "path": f"/home/"
                                f"{nt.get('admin_username', 'ray')}"
                                f"/.ssh/authorized_keys",
                        "key_data": nt["ssh_public_key"]}]},
                }} if nt.get("ssh_public_key") else {}),
            },
        }
        poller = self._compute.virtual_machines.begin_create_or_update(
            self.resource_group, vm_name, params)
        # the reference also blocks on the LRO before recording the node
        poller.result()
        self._type_by_id[vm_name] = node_type
        return vm_name

    def terminate_node(self, provider_node_id: str) -> None:
        self._compute.virtual_machines.begin_delete(
            self.resource_group, provider_node_id)
        self._type_by_id.pop(provider_node_id, None)

    def non_terminated_nodes(self) -> List[str]:
        names = []
        for vm in self._compute.virtual_machines.list(
                self.resource_group):
            tags = getattr(vm, "tags", None) or {}
            if tags.get(TAG_CLUSTER) != self.cluster_name:
                continue
            state = getattr(vm, "provisioning_state", "Succeeded")
            if state in ("Deleting", "Failed"):
                continue
            names.append(vm.name)
            # rebuild the type map across provider restarts from tags
            if TAG_NODE_TYPE in tags:
                self._type_by_id[vm.name] = tags[TAG_NODE_TYPE]
        return names

    def node_type_of(self, node_id: str) -> Optional[str]:
        return self._type_by_id.get(node_id)

    # -- wiring ---------------------------------------------------------------
    def _bootstrap(self, nt: Dict[str, Any]) -> str:
        res = dict(nt.get("host_resources", _DEFAULT_RESOURCES))
        extra = nt.get("setup_commands", [])
        join = (f"ray-tpu start --address "
                f"{shlex.quote(self.head_address)} "
                f"--num-cpus {int(res.get('CPU', 4))}")
        return "#!/bin/bash\n" + "\n".join([*extra, join]) + "\n"
