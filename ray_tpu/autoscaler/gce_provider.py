"""GCE VM node provider: scale with plain Compute Engine instances.

Capability mirror of the reference's GCP provider
(/root/reference/python/ray/autoscaler/_private/gcp/node_provider.py) for
the CPU-worker side of a TPU cluster (data loading, preprocessing,
rollout workers — anything that doesn't need chips).  Same design as
`tpu_pod_provider.py`: all cloud mutations go through the ``gcloud`` CLI
(zero SDK dependencies; unit tests inject a fake runner), and every
created instance boots a startup script that joins the cluster with
``ray-tpu start --address <head>``.
"""

from __future__ import annotations

import json
import shlex
from typing import Any, Callable, Dict, List, Optional

from .node_provider import NodeProvider
from .tpu_pod_provider import _run_gcloud

_DEFAULT_RESOURCES = {"CPU": 4.0}


class GceProvider(NodeProvider):
    """Provision/terminate worker VMs via ``gcloud compute instances``.

    node_types maps a logical name to the instance shape, e.g.::

        {"cpu_16": {"machine_type": "n2-standard-16",
                    "host_resources": {"CPU": 16}},
         "highmem": {"machine_type": "n2-highmem-8",
                     "image_family": "debian-12",
                     "image_project": "debian-cloud"}}
    """

    def __init__(self, *, project: str, zone: str, head_address: str,
                 node_types: Dict[str, Dict[str, Any]],
                 name_prefix: str = "ray-tpu-w",
                 runner: Optional[Callable[[List[str]], str]] = None):
        self.project = project
        self.zone = zone
        self.head_address = head_address
        self.node_types = node_types
        self.name_prefix = name_prefix
        self._run = runner or _run_gcloud
        self._seq = 0

    # -- provider contract ---------------------------------------------------
    def node_resources(self, node_type: str) -> Dict[str, float]:
        nt = self.node_types[node_type]
        return dict(nt.get("host_resources", _DEFAULT_RESOURCES))

    def create_node(self, node_type: str) -> str:
        nt = self.node_types[node_type]
        self._seq += 1
        name = f"{self.name_prefix}-{node_type}-{self._seq}".replace(
            "_", "-")
        args = [
            "compute", "instances", "create", name,
            "--project", self.project, "--zone", self.zone,
            "--machine-type", nt.get("machine_type", "n2-standard-4"),
            # comma-safe custom delimiter (see tpu_pod_provider)
            "--metadata",
            f"^|@|^startup-script={self._startup_script(nt)}",
        ]
        if nt.get("image_family"):
            args += ["--image-family", nt["image_family"]]
        if nt.get("image_project"):
            args += ["--image-project", nt["image_project"]]
        self._run(args, timeout=600.0)
        return name

    def terminate_node(self, provider_node_id: str) -> None:
        self._run([
            "compute", "instances", "delete", provider_node_id,
            "--project", self.project, "--zone", self.zone, "--quiet",
        ], timeout=600.0)

    def non_terminated_nodes(self) -> List[str]:
        out = self._run([
            "compute", "instances", "list",
            "--project", self.project,
            "--zones", self.zone,
            "--format", "json",
        ])
        nodes = json.loads(out or "[]")
        return [n["name"] for n in nodes
                if n["name"].startswith(self.name_prefix)
                and n.get("status") in ("RUNNING", "PROVISIONING",
                                        "STAGING", None)]

    # -- wiring ---------------------------------------------------------------
    def _startup_script(self, nt: Dict[str, Any]) -> str:
        extra = nt.get("setup_commands", [])
        res = dict(nt.get("host_resources", _DEFAULT_RESOURCES))
        join = (f"ray-tpu start --address "
                f"{shlex.quote(self.head_address)} "
                f"--num-cpus {int(res.get('CPU', 4))}")
        return "#! /bin/bash\n" + "\n".join([*extra, join]) + "\n"
