"""TPU-native operator library.

The reference delegates all accelerator math to torch/tf (SURVEY.md §2.3);
here the hot ops are first-class: fused attention (Pallas flash kernel with a
reference jnp fallback), rotary embeddings, and normalizations.  Everything is
jit-traceable with static shapes so XLA can tile onto the MXU.
"""

from .norms import layernorm, rmsnorm  # noqa: F401
from .rotary import apply_rotary, rotary_angles  # noqa: F401
from .attention import multi_head_attention, reference_attention  # noqa: F401
from .flash_attention import flash_attention  # noqa: F401
