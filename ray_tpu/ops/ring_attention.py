"""Sequence-parallel attention: ring (ppermute) and Ulysses (all_to_all).

The reference has NO long-context strategy (SURVEY.md §5.7 — sequence
scaling is delegated to user frameworks); here it is a first-class op pair
on the ``sp`` mesh axis:

  * **Ring attention** — K/V shards rotate around the ICI ring
    (`lax.ppermute`) while each device accumulates blockwise online-softmax
    statistics for its local queries.  Memory per device is O(S/n · S/n);
    the rotation overlaps with compute under XLA pipelining.  Causality is
    enforced with rank-relation masks, so the op is fully differentiable
    (no custom VJP needed — gradients flow through ppermute).
  * **Ulysses** — all_to_all re-shards from sequence to heads, runs dense
    local attention (the Pallas flash kernel when on TPU), and re-shards
    back.  Cheaper at moderate S, needs head_count % sp == 0.

Both are written against `shard_map` shards: ``*_shard`` functions take
LOCAL arrays [batch, seq_local, heads, head_dim] and must run inside
`shard_map` (or any SPMD region) over the named axis.  `make_ring_attention`
/ `make_ulysses_attention` wrap them for whole-array use on a mesh.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

_NEG = -1e30


def _online_block(q, k, v, mask, sm_scale, m, l, acc):
    """One blockwise online-softmax accumulation step (fp32 stats).
    q:[B,Sq,H,D] k/v:[B,Sk,H,D] mask broadcastable to [B,H,Sq,Sk]."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    s = jnp.where(mask, s, _NEG)
    m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new)
    l_new = l * alpha + p.sum(axis=-1, keepdims=True)
    pv = jnp.einsum("bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
    acc_new = acc * alpha[..., 0][..., None] + pv
    return m_new, l_new, acc_new


def ring_attention_shard(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                         axis_name: str = "sp", axis_size: int,
                         causal: bool = True,
                         sm_scale: Optional[float] = None) -> jnp.ndarray:
    """Ring attention over LOCAL shards (call inside shard_map over
    ``axis_name``).  Shapes [B, S/n, H, D]; KV heads may divide Q heads."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    n_rep = q.shape[2] // k.shape[2]
    if n_rep > 1:
        from .attention import repeat_kv
        k, v = repeat_kv(k, n_rep), repeat_kv(v, n_rep)
    b, s_loc, h, d = q.shape
    my = jax.lax.axis_index(axis_name)

    rows = jnp.arange(s_loc)[:, None]
    cols = jnp.arange(s_loc)[None, :]
    diag_mask = rows >= cols                      # within-chunk causal

    m = jnp.full((b, h, s_loc, 1), _NEG, jnp.float32)
    l = jnp.zeros((b, h, s_loc, 1), jnp.float32)
    acc = jnp.zeros((b, h, s_loc, d), jnp.float32)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def body(i, carry):
        m, l, acc, k_cur, v_cur = carry
        src = (my - i) % axis_size                # owner rank of k_cur
        if causal:
            mask = jnp.where(src < my, True, False) | \
                   ((src == my) & diag_mask)
            mask = jnp.broadcast_to(mask, (b, h, s_loc, s_loc))
        else:
            mask = jnp.ones((b, h, s_loc, s_loc), bool)
        m2, l2, acc2 = _online_block(q, k_cur, v_cur, mask, sm_scale,
                                     m, l, acc)
        # fully-masked steps (src > my under causal) must not touch stats
        if causal:
            skip = src > my
            m2 = jnp.where(skip, m, m2)
            l2 = jnp.where(skip, l, l2)
            acc2 = jnp.where(skip, acc, acc2)
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return m2, l2, acc2, k_next, v_next

    carry = (m, l, acc, k, v)
    for i in range(axis_size):                    # static unroll: n steps
        carry = body(i, carry)
    m, l, acc, _, _ = carry
    out = acc / jnp.maximum(l, 1e-30)
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


def ulysses_attention_shard(q: jnp.ndarray, k: jnp.ndarray,
                            v: jnp.ndarray, *, axis_name: str = "sp",
                            causal: bool = True,
                            sm_scale: Optional[float] = None,
                            inner_impl: str = "auto") -> jnp.ndarray:
    """Ulysses SP: seq-sharded [B, S/n, H, D] → heads-sharded full-seq
    attention → seq-sharded output.  Requires H % n == 0."""
    from .attention import multi_head_attention

    def a2a(x, split, concat):
        return jax.lax.all_to_all(x, axis_name, split_axis=split,
                                  concat_axis=concat, tiled=True)

    q_h = a2a(q, 2, 1)     # [B, S, H/n, D]
    k_h = a2a(k, 2, 1)
    v_h = a2a(v, 2, 1)
    out = multi_head_attention(q_h, k_h, v_h, causal=causal,
                               sm_scale=sm_scale, impl=inner_impl)
    return a2a(out, 1, 2)  # back to [B, S/n, H, D]


# -- whole-array wrappers ----------------------------------------------------

def make_ring_attention(mesh: Mesh, axis_name: str = "sp"):
    """(q, k, v) → out with q/k/v whole arrays sharded [B, S@sp, H, D]."""
    axis_size = dict(zip(mesh.axis_names, mesh.devices.shape))[axis_name]
    spec = P(None, axis_name, None, None)

    @jax.jit
    def fn(q, k, v):
        shard = functools.partial(ring_attention_shard,
                                  axis_name=axis_name,
                                  axis_size=axis_size)
        return jax.shard_map(shard, mesh=mesh,
                             in_specs=(spec, spec, spec),
                             out_specs=spec)(q, k, v)

    return fn


def make_ulysses_attention(mesh: Mesh, axis_name: str = "sp",
                           inner_impl: str = "auto"):
    spec = P(None, axis_name, None, None)

    @jax.jit
    def fn(q, k, v):
        shard = functools.partial(ulysses_attention_shard,
                                  axis_name=axis_name,
                                  inner_impl=inner_impl)
        return jax.shard_map(shard, mesh=mesh,
                             in_specs=(spec, spec, spec),
                             out_specs=spec)(q, k, v)

    return fn


def ring_attention(q, k, v, *, causal: bool = True,
                   sm_scale: Optional[float] = None,
                   axis_name: str = "sp", axis_size: Optional[int] = None):
    """Shard-level entry used by the model's attention dispatch: must be
    traced inside an SPMD region over ``axis_name``.  ``axis_size`` falls
    back to the bound axis size."""
    if axis_size is None:
        axis_size = jax.lax.psum(1, axis_name)
        if not isinstance(axis_size, int):
            raise ValueError(
                "ring attention needs a static axis_size; pass it or call "
                "through make_ring_attention(mesh)")
    return ring_attention_shard(q, k, v, axis_name=axis_name,
                                axis_size=axis_size, causal=causal,
                                sm_scale=sm_scale)
