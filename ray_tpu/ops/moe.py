"""Mixture-of-experts FFN with top-k routing and capacity-based dispatch.

The reference has no expert parallelism at all (SURVEY.md §2.4 row 5 —
"Absent"); this is the TPU-native deliverable for that row.  The design is
the GShard/Switch einsum formulation, which is what maps onto the MXU and
onto GSPMD's all_to_all insertion:

  * router logits → top-k gate weights per token,
  * a dense one-hot *dispatch* tensor [batch, seq, experts, capacity]
    scatters tokens into per-expert buffers (einsum, no gather loops),
  * expert FFNs run batched over a leading ``expert`` axis — sharding that
    axis over the mesh's ``ep`` axis makes XLA insert the all_to_all
    dispatch/combine pair over ICI,
  * a *combine* tensor (same shape, gate-weighted) merges expert outputs
    back to token order.

Tokens beyond an expert's capacity are dropped (their combine weight is
zero and the residual connection carries them through unchanged) — the
standard Switch-Transformer overflow policy.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp


def expert_capacity(seq_tokens: int, n_experts: int, top_k: int,
                    capacity_factor: float) -> int:
    """Per-expert buffer size: ``ceil(tokens * k / E * factor)`` rounded up
    to a multiple of 8 (TPU sublane alignment)."""
    cap = math.ceil(seq_tokens * top_k * capacity_factor / n_experts)
    return max(8, ((cap + 7) // 8) * 8)


def route(y: jnp.ndarray, router_w: jnp.ndarray, top_k: int,
          capacity: int):
    """Compute dispatch/combine tensors.

    y: [b, s, d] activations; router_w: [d, E].
    Returns (dispatch [b,s,E,C] bool-ish, combine [b,s,E,C] float32,
    aux_loss scalar) where aux_loss is the Switch load-balancing loss.
    """
    b, s, _ = y.shape
    n_experts = router_w.shape[-1]
    logits = jnp.einsum("bsd,de->bse", y.astype(jnp.float32),
                        router_w.astype(jnp.float32))
    gates = jax.nn.softmax(logits, axis=-1)                     # [b,s,E]
    gate_k, idx_k = jax.lax.top_k(gates, top_k)                 # [b,s,k]
    gate_k = gate_k / jnp.maximum(gate_k.sum(-1, keepdims=True), 1e-9)

    onehot = jax.nn.one_hot(idx_k, n_experts, dtype=jnp.float32)  # [b,s,k,E]
    # Position of each (token, choice) within its expert's buffer: running
    # count over the flattened (s*k) selection order.
    flat = onehot.reshape(b, s * top_k, n_experts)
    pos = (jnp.cumsum(flat, axis=1) - flat).reshape(b, s, top_k, n_experts)
    within = (pos < capacity).astype(jnp.float32) * onehot      # [b,s,k,E]
    pos_oh = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)   # [b,s,k,E,C]
    # combine[b,s,e,c] = sum_k gate_k * 1[expert k == e] * 1[slot k == c]
    combine = jnp.einsum("bsk,bske,bskec->bsec",
                         gate_k, within, pos_oh)                # [b,s,E,C]
    dispatch = (combine > 0.0).astype(y.dtype)

    # Switch load-balancing aux loss: E * sum_e f_e * p_e where f_e is the
    # fraction of tokens routed (top-1) to e and p_e the mean gate prob.
    top1 = jax.nn.one_hot(idx_k[..., 0], n_experts, dtype=jnp.float32)
    aux = n_experts * jnp.mean(
        jnp.mean(top1, axis=(0, 1)) * jnp.mean(gates, axis=(0, 1)))
    return dispatch, combine, aux


def moe_ffn(y: jnp.ndarray, router_w: jnp.ndarray, w_in: jnp.ndarray,
            w_out: jnp.ndarray, w_gate: Optional[jnp.ndarray] = None, *,
            top_k: int = 2, capacity_factor: float = 2.0,
            constrain=None):
    """MoE feed-forward block.

    y [b,s,d]; router_w [d,E]; w_in [E,d,f]; w_out [E,f,d];
    w_gate [E,d,f] selects SwiGLU (None → GELU).
    Returns (out [b,s,d], aux_loss).  ``constrain(x, logical_axes)`` is an
    optional sharding-constraint hook — the expert-major intermediates get
    ("expert", ...) so the `ep` mesh axis produces all_to_alls.
    """
    b, s, d = y.shape
    n_experts = w_in.shape[0]
    dt = y.dtype
    cap = expert_capacity(s, n_experts, top_k, capacity_factor)
    dispatch, combine, aux = route(y, router_w, top_k, cap)

    # dispatch: token-major → expert-major [E, b, C, d] (GSPMD all_to_all
    # happens here when `ep` shards the leading axis and batch shards b)
    xe = jnp.einsum("bsec,bsd->ebcd", dispatch.astype(dt), y)
    if constrain is not None:
        xe = constrain(xe, ("expert", "batch", None, None))
    up = jnp.einsum("ebcd,edf->ebcf", xe, w_in.astype(dt))
    if w_gate is not None:
        gate = jnp.einsum("ebcd,edf->ebcf", xe, w_gate.astype(dt))
        z = jax.nn.silu(gate) * up
    else:
        z = jax.nn.gelu(up)
    oe = jnp.einsum("ebcf,efd->ebcd", z, w_out.astype(dt))
    if constrain is not None:
        oe = constrain(oe, ("expert", "batch", None, None))
    out = jnp.einsum("ebcd,bsec->bsd", oe, combine.astype(dt))
    return out, aux


def moe_ffn_reference(y, router_w, w_in, w_out, w_gate=None, *, top_k=2):
    """Slow per-token loop-free reference (no capacity limit): every token
    is processed by its top-k experts exactly.  Used by tests to validate
    the dispatch-einsum path (which must agree when capacity is ample)."""
    b, s, d = y.shape
    n_experts = w_in.shape[0]
    f32 = jnp.float32
    gates = jax.nn.softmax(jnp.einsum("bsd,de->bse", y.astype(f32),
                                      router_w.astype(f32)), axis=-1)
    gate_k, idx_k = jax.lax.top_k(gates, top_k)
    gate_k = gate_k / jnp.maximum(gate_k.sum(-1, keepdims=True), 1e-9)
    yf = y.astype(f32)
    up = jnp.einsum("bsd,edf->bsef", yf, w_in.astype(f32))
    if w_gate is not None:
        g = jnp.einsum("bsd,edf->bsef", yf, w_gate.astype(f32))
        z = jax.nn.silu(g) * up
    else:
        z = jax.nn.gelu(up)
    all_out = jnp.einsum("bsef,efd->bsed", z, w_out.astype(f32))  # [b,s,E,d]
    weight = jnp.einsum("bsk,bske->bse", gate_k,
                        jax.nn.one_hot(idx_k, n_experts, dtype=f32))
    return jnp.einsum("bsed,bse->bsd", all_out, weight).astype(y.dtype)
