"""Normalization ops.

Plain jnp on purpose: XLA fuses norm → matmul chains into the surrounding
HLO better than a hand-written kernel boundary would allow (pallas_call is a
fusion barrier).  Accumulation is fp32 even for bf16 activations.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp


def rmsnorm(x: jnp.ndarray, scale: jnp.ndarray, eps: float = 1e-6) -> jnp.ndarray:
    """RMSNorm (Llama family).  fp32 statistics, output in x.dtype."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    y = x32 * jnp.reciprocal(jnp.sqrt(var + eps))
    return (y * scale.astype(jnp.float32)).astype(dtype)


def layernorm(x: jnp.ndarray, scale: jnp.ndarray,
              bias: Optional[jnp.ndarray] = None,
              eps: float = 1e-5) -> jnp.ndarray:
    """LayerNorm (GPT-2 family).  fp32 statistics, output in x.dtype."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    y = (x32 - mean) * jnp.reciprocal(jnp.sqrt(var + eps))
    y = y * scale.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(dtype)
