"""Attention dispatch: Pallas flash kernel on TPU, reference jnp elsewhere.

All shapes are ``[batch, seq, heads, head_dim]`` with KV heads a divisor of
query heads (GQA).  `multi_head_attention` picks the implementation:

  * ``"flash"``  — `ray_tpu.ops.flash_attention` (TPU Pallas kernel)
  * ``"reference"`` — pure jnp (XLA-fused; used on CPU and for odd shapes)
  * ``"ring"``   — sequence-parallel ring attention
    (`ray_tpu.ops.ring_attention`, shards over the ``sp`` mesh axis)
  * ``"auto"``   — flash when on TPU and shapes are block-aligned
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .flash_attention import _default_blocks, fit_block, flash_attention


def repeat_kv(x: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """Expand [b, s, h_kv, d] → [b, s, h_kv*n_rep, d] for GQA fallbacks."""
    if n_rep == 1:
        return x
    b, s, h, d = x.shape
    return jnp.broadcast_to(x[:, :, :, None, :],
                            (b, s, h, n_rep, d)).reshape(b, s, h * n_rep, d)


def reference_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                        causal: bool = True,
                        sm_scale: Optional[float] = None) -> jnp.ndarray:
    """Plain softmax(QKᵀ)V with fp32 statistics; the correctness oracle for
    the flash kernel and the CPU execution path."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    n_rep = q.shape[2] // k.shape[2]
    k, v = repeat_kv(k, n_rep), repeat_kv(v, n_rep)
    # [b, h, s_q, s_k]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        s_q, s_k = q.shape[1], k.shape[1]
        rows = jnp.arange(s_q)[:, None] + (s_k - s_q)
        mask = rows >= jnp.arange(s_k)[None, :]
        # additive bias rather than jnp.where: a select against an invariant
        # constant inside a partial-manual shard_map scan (the pp pipeline)
        # trips an XLA partitioner CHECK ("invalid binary opcode copy");
        # adds fuse into the matmul epilogue anyway
        s = s + (1.0 - mask.astype(jnp.float32)) * -1e30
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def _flash_ok(q: jnp.ndarray, k: jnp.ndarray) -> bool:
    if jax.default_backend() != "tpu":
        return False
    s_q, s_kv, d = q.shape[1], k.shape[1], q.shape[-1]
    if d % 64:
        return False
    dbq, dbk = _default_blocks()
    bq, bk = fit_block(dbq, s_q), fit_block(dbk, s_kv)
    # eligible when a block no smaller than the configured one (capped at
    # the classic 128 floor) divides the seq, or the whole (short) seq is
    # one block — so env-configured sub-128 sweeps still take the flash
    # path instead of silently measuring unfused attention
    return (bq >= min(128, dbq) or bq == s_q) and \
        (bk >= min(128, dbk) or bk == s_kv)


def multi_head_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                         causal: bool = True,
                         sm_scale: Optional[float] = None,
                         impl: str = "auto") -> jnp.ndarray:
    if impl == "auto":
        impl = "flash" if _flash_ok(q, k) else "reference"
    if impl == "flash":
        return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    if impl == "reference":
        return reference_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    if impl == "ring":
        # sequence-parallel path: shard_map over the ambient mesh's sp axis
        # (set the mesh with `jax.set_mesh` / `with mesh:` around the jit)
        import functools

        from jax.sharding import PartitionSpec as P

        from .ring_attention import ring_attention_shard
        mesh = jax.sharding.get_abstract_mesh()
        sp = dict(mesh.shape).get("sp", 1) if mesh is not None else 1
        if sp <= 1:
            return reference_attention(q, k, v, causal=causal,
                                       sm_scale=sm_scale)
        spec = P(None, "sp", None, None)
        return jax.shard_map(
            functools.partial(ring_attention_shard, axis_name="sp",
                              axis_size=sp, causal=causal,
                              sm_scale=sm_scale),
            in_specs=(spec, spec, spec), out_specs=spec)(q, k, v)
    raise ValueError(f"unknown attention impl {impl!r}")
