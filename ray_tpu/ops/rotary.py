"""Rotary position embeddings (RoPE), applied in fp32.

Shapes follow the framework convention: activations are
``[batch, seq, heads, head_dim]``.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp


def rotary_angles(seq_len: int, head_dim: int, base: float = 10000.0,
                  offset: int = 0) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(cos, sin) tables of shape [seq_len, head_dim//2]."""
    inv_freq = 1.0 / (base ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32)
                               / head_dim))
    pos = jnp.arange(offset, offset + seq_len, dtype=jnp.float32)
    angles = jnp.outer(pos, inv_freq)
    return jnp.cos(angles), jnp.sin(angles)


def apply_rotary(x: jnp.ndarray, cos: jnp.ndarray,
                 sin: jnp.ndarray) -> jnp.ndarray:
    """Rotate [batch, seq, heads, head_dim] by per-position angles.

    Uses the split-halves convention (rotate_half), matching the Llama
    family.  cos/sin are [seq, head_dim//2].
    """
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    half = x.shape[-1] // 2
    x1, x2 = x32[..., :half], x32[..., half:]
    c = cos[None, :, None, :]
    s = sin[None, :, None, :]
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(dtype)
