"""Blockwise fused attention (flash attention) as a Pallas TPU kernel.

The reference framework has no fused attention of its own — it delegates all
model math to torch (SURVEY.md §2.3); in a TPU-native stack the attention
inner loop is the single hottest op, so it gets a hand-written kernel:

  * online-softmax forward with fp32 accumulators in VMEM scratch,
  * custom-VJP backward (separate dq and dk/dv kernels),
  * grouped-query attention handled by index maps (no KV repetition),
  * causal blocks above the diagonal skipped via ``pl.when``.

Inputs are ``[batch, seq, heads, head_dim]`` (framework activation layout);
the kernel operates in ``[batch, heads, seq, head_dim]``.  bf16 in/out, fp32
softmax statistics.  Sequence length must be divisible by the block sizes —
callers (`ray_tpu.ops.attention.multi_head_attention`) fall back to the
reference jnp implementation otherwise.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific pallas extensions (memory spaces, compiler params)
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    _HAS_PLTPU = False

# Interpreter-mode switch: RAY_TPU_PALLAS_INTERPRET=1 runs the kernels
# through the Pallas interpreter (any backend) — the off-chip validation
# path for kernel logic (tests use it so the kernel math is proven even
# when no TPU is attached).
import os as _os

def _interpret() -> bool:
    return _os.environ.get("RAY_TPU_PALLAS_INTERPRET") == "1"


DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 512
_NEG_INF = -1e30  # avoids -inf - -inf = nan in the online softmax


def _env_block(name: str, default: int) -> int:
    raw = _os.environ.get(name)
    if raw is None:
        return default
    try:
        val = int(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r}: must be a positive integer")
    if val < 8:
        raise ValueError(f"{name}={val}: flash block sizes must be >= 8")
    return val


def _default_blocks() -> Tuple[int, int]:
    """Block sizes resolve at trace time, overridable via env
    (RAY_TPU_FLASH_BLOCK_Q/K) for on-chip tuning sweeps.  Defaults were
    measured on v5e (gpt2-small train step): 128x128 made the grid so
    fine (b*h*8*8 = 6k steps per layer call) that per-step fixed costs
    beat the MXU work; 256x512 keeps VMEM modest (score block = 512 KiB
    fp32) with 16x fewer grid steps."""
    return (_env_block("RAY_TPU_FLASH_BLOCK_Q", DEFAULT_BLOCK_Q),
            _env_block("RAY_TPU_FLASH_BLOCK_K", DEFAULT_BLOCK_K))


def fit_block(block: int, s: int) -> int:
    """Largest block <= ``block`` that divides ``s`` (halving search, so a
    128-aligned sequence shorter than the default still lands on a
    128-multiple block instead of being rejected)."""
    b = min(block, s)
    while b > 1 and s % b:
        b //= 2
    return b


def _dims(q, k):
    b, h, s_q, d = q.shape
    h_kv, s_kv = k.shape[1], k.shape[2]
    assert h % h_kv == 0, f"query heads {h} not a multiple of kv heads {h_kv}"
    return b, h, h_kv, h // h_kv, s_q, s_kv, d


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref, *,
                sm_scale, causal, block_q, block_k, num_k, q_offset):
    qi, ki = pl.program_id(2), pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    live = ((ki * block_k <= qi * block_q + block_q - 1 + q_offset)
            if causal else (ki >= 0))

    @pl.when(live)
    def _compute():
        # MXU-native precision: keep inputs in their storage dtype (bf16)
        # and accumulate fp32 via preferred_element_type — casting inputs
        # to fp32 first would force the multi-pass fp32 MXU path (~4-8x
        # slower; measured 0.9x vs unfused attention on v5e before this).
        s = jax.lax.dot_general(q_ref[0, 0], k_ref[0, 0],
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            rows = q_offset + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m_prev = m_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        # A row fully masked within a live block (causal with s_q > s_kv:
        # rows above the diagonal of their first k-block) has m_new ==
        # _NEG_INF, making exp(s - m_new) == 1 for every masked column —
        # zero those rows instead of averaging V uniformly.
        p = jnp.where(m_new <= _NEG_INF * 0.5, 0.0, jnp.exp(s - m_new))
        l_ref[...] = jnp.broadcast_to(
            alpha * l_ref[:, :1] + jnp.sum(p, axis=1, keepdims=True),
            l_ref.shape)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        pv = jax.lax.dot_general(p.astype(v_ref.dtype), v_ref[0, 0],
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_ref[...] = acc_ref[...] * alpha + pv

    @pl.when(ki == num_k - 1)
    def _finish():
        l = l_ref[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[...] / safe_l).astype(o_ref.dtype)
        # Dead rows (m still _NEG_INF) get lse = 0 so the backward kernels'
        # exp(s - lse) = exp(_NEG_INF) underflows to zero gradient; the
        # natural m + log(l) would be ~ -1e30 - 69, making s - lse positive.
        m = m_ref[:, :1]
        lse_ref[0, 0] = jnp.where(
            m <= _NEG_INF * 0.5, 0.0,
            m + jnp.log(jnp.maximum(l_ref[:, :1], 1e-30)))


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    b, h, h_kv, group, s_q, s_kv, d = _dims(q, k)
    num_q, num_k = s_q // block_q, s_kv // block_k
    grid = (b, h, num_q, num_k)

    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, num_k=num_k,
        q_offset=s_kv - s_q)
    out_shapes = (
        jax.ShapeDtypeStruct((b, h, s_q, d), q.dtype),
        jax.ShapeDtypeStruct((b, h, s_q, 1), jnp.float32),
    )
    compiler_params = None
    if _HAS_PLTPU:
        compiler_params = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"))
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        interpret=_interpret(),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, qi, ki, g=group: (b_, h_ // g, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, qi, ki, g=group: (b_, h_ // g, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ] if _HAS_PLTPU else [],
        out_shape=out_shapes,
        compiler_params=compiler_params,
    )(q, k, v)
    return o, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_acc, *, sm_scale, causal, block_q, block_k, num_k,
                   q_offset):
    qi, ki = pl.program_id(2), pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    live = ((ki * block_k <= qi * block_q + block_q - 1 + q_offset)
            if causal else (ki >= 0))

    @pl.when(live)
    def _compute():
        # bf16 MXU inputs + fp32 accumulation throughout (see _fwd_kernel)
        k = k_ref[0, 0]
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(q_ref[0, 0], k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            rows = q_offset + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do_ref[0, 0], v_ref[0, 0],
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * sm_scale).astype(k.dtype)
        dq_acc[...] += jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                           preferred_element_type=jnp.float32)

    @pl.when(ki == num_k - 1)
    def _finish():
        dq_ref[0, 0] = dq_acc[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *,
                    sm_scale, causal, block_q, block_k, num_q, group,
                    q_offset):
    ki, gi, qi = pl.program_id(2), pl.program_id(3), pl.program_id(4)

    @pl.when((qi == 0) & (gi == 0))
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    live = ((qi * block_q + block_q - 1 + q_offset >= ki * block_k)
            if causal else (qi >= 0))

    @pl.when(live)
    def _compute():
        # bf16 MXU inputs + fp32 accumulation throughout (see _fwd_kernel)
        q = q_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(q, k_ref[0, 0], (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            rows = q_offset + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse)                                   # [bq, bk]
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                # [bk, d]
        dp = jax.lax.dot_general(do, v_ref[0, 0], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * sm_scale).astype(q.dtype)
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                # [bk, d]

    @pl.when((qi == num_q - 1) & (gi == group - 1))
    def _finish():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, causal, sm_scale, block_q, block_k):
    b, h, h_kv, group, s_q, s_kv, d = _dims(q, k)
    num_q, num_k = s_q // block_q, s_kv // block_k
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)                    # [b, h, s_q, 1]

    sem = (("parallel", "parallel", "parallel", "arbitrary")
           if _HAS_PLTPU else None)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_k=num_k,
                          q_offset=s_kv - s_q),
        grid=(b, h, num_q, num_k),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, qi, ki, g=group: (b_, h_ // g, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, qi, ki, g=group: (b_, h_ // g, ki, 0)),
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)]
        if _HAS_PLTPU else [],
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        compiler_params=pltpu.CompilerParams(dimension_semantics=sem)
        if _HAS_PLTPU else None,
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    sem5 = (("parallel", "parallel", "parallel", "arbitrary", "arbitrary")
            if _HAS_PLTPU else None)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_q=num_q,
                          group=group, q_offset=s_kv - s_q),
        grid=(b, h_kv, num_k, group, num_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h2, ki, g_, qi, G=group: (b_, h2 * G + g_, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h2, ki, g_, qi: (b_, h2, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h2, ki, g_, qi: (b_, h2, ki, 0)),
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h2, ki, g_, qi, G=group: (b_, h2 * G + g_, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b_, h2, ki, g_, qi, G=group: (b_, h2 * G + g_, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b_, h2, ki, g_, qi, G=group: (b_, h2 * G + g_, qi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h2, ki, g_, qi: (b_, h2, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h2, ki, g_, qi: (b_, h2, ki, 0)),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)]
        if _HAS_PLTPU else [],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        compiler_params=pltpu.CompilerParams(dimension_semantics=sem5)
        if _HAS_PLTPU else None,
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom-vjp wrapper (operates in [b, h, s, d])
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, sm_scale, block_q, block_k):
    o, _ = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k)
    return o


def _flash_fwd_rule(q, k, v, causal, sm_scale, block_q, block_k):
    o, lse = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k)
    return o, (q, k, v, o, lse)


def _flash_bwd_rule(causal, sm_scale, block_q, block_k, res, do):
    q, k, v, o, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, o, lse, do, causal, sm_scale,
                            block_q, block_k)
    return dq, dk, dv


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                    causal: bool = True,
                    sm_scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None) -> jnp.ndarray:
    """Fused attention over ``[batch, seq, heads, head_dim]`` inputs.

    KV heads may be a divisor of query heads (GQA/MQA).  Differentiable via
    flash backward kernels.  Block sizes default from `_default_blocks()`
    (env-tunable) when not given, and are clamped (halving search) to the
    largest divisor of each seq length; raises only when no divisor >= 8
    exists — use `multi_head_attention` for automatic fallback.
    """
    dq, dk_ = _default_blocks()
    if block_q is None:
        block_q = dq
    if block_k is None:
        block_k = dk_
    s_q, s_kv = q.shape[1], k.shape[1]
    bq, bk = fit_block(block_q, s_q), fit_block(block_k, s_kv)
    if bq < 8 or bk < 8:   # no MXU-reasonable divisor exists
        raise ValueError(
            f"seq lengths ({s_q}, {s_kv}) have no block divisor >= 8 "
            f"under ({block_q}, {block_k})")
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    # the kernels feed q/k/v straight into MXU dots in their storage dtype
    # (bf16 in + fp32 accumulation); normalize mixed-dtype inputs (e.g. an
    # fp32 query against a bf16 KV cache) to the query's dtype up front
    k = k.astype(q.dtype)
    v = v.astype(q.dtype)
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out = _flash(qt, kt, vt, causal, sm_scale, bq, bk)
    return jnp.swapaxes(out, 1, 2)
