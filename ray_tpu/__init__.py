"""ray_tpu — a TPU-native distributed computing framework.

Tasks, actors, and a shared-memory object store on a multi-node runtime
(controller + per-node nodelets), with JAX/XLA as the accelerator data plane:
device-mesh collectives over ICI instead of NCCL, pjit/shard_map sharding
instead of DDP wrappers, and TPU-topology-aware placement groups.

Capability mirror of Ray (see SURVEY.md for the layer map); architecture is
TPU-first, not a port.
"""

from .api import (  # noqa: F401
    ActorClass,
    ActorHandle,
    ClientContext,
    RemoteFunction,
    available_resources,
    cancel,
    cluster_resources,
    cpp_actor,
    cpp_function,
    get,
    get_actor,
    get_runtime_context,
    get_tpu_ids,
    init,
    is_initialized,
    kill,
    nodes,
    put,
    remote,
    shutdown,
    timeline,
    wait,
)
from .core.driver import ObjectRef, ObjectRefGenerator  # noqa: F401
from . import exceptions  # noqa: F401
from .dag.node import install_bind as _install_bind

_install_bind()
del _install_bind

__version__ = "0.1.0"
