"""User-facing error types (reference: python/ray/exceptions.py)."""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception; re-raised at every ``get`` of its returns.

    Mirrors the reference's RayTaskError: carries the remote traceback and,
    when picklable, the original exception as ``cause``.
    """

    def __init__(self, function_name: str, traceback_str: str,
                 cause: Exception | None = None):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(f"task {function_name} failed:\n{traceback_str}")


class ActorError(TaskError):
    pass


class ActorDiedError(RayTpuError):
    def __init__(self, actor_id_hex: str, reason: str):
        self.actor_id_hex = actor_id_hex
        self.reason = reason
        who = f"actor {actor_id_hex[:12]}" if actor_id_hex else "actor"
        super().__init__(f"{who} died: {reason}")


class WorkerCrashedError(RayTpuError):
    """The worker process executing a task died unexpectedly."""


class ObjectLostError(RayTpuError):
    def __init__(self, object_id_hex: str, detail: str = ""):
        super().__init__(f"object {object_id_hex[:16]} is lost: {detail}")


# A cross-node fetch that exhausted its retry / alternate-copy / relay
# ladder raises this typed error carrying every attempted source; it is
# defined next to the store client (the layer that fetches) and
# re-exported here as user-facing API.
from .core.object_store.client import ObjectFetchError  # noqa: E402,F401


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class ReplicaUnavailableError(RayTpuError):
    """A Serve deployment currently has zero live replicas.

    Typed fast-shed signal: the router raises it immediately instead of
    busy-polling its table until the request deadline, and the HTTP
    proxy maps it to 503 + ``Retry-After`` so load balancers back off
    instead of piling on a deployment that is restarting."""

    def __init__(self, deployment: str, retry_after_s: float = 1.0):
        self.deployment = deployment
        self.retry_after_s = retry_after_s
        super().__init__(
            f"no live replicas for deployment {deployment!r} "
            f"(retry after ~{retry_after_s:g}s)")


class ControlPlaneOverloadError(RayTpuError):
    """The controller shed a bulk-lane op under overload (brownout).

    Typed retriable pushback carrying ``Retry-After``: clients replay
    the op with full-jitter backoff until the controller's watermark
    state machine recovers; only a shed that outlives the whole
    failover/backoff budget surfaces as this exception."""

    def __init__(self, op: str, retry_after_s: float = 1.0):
        self.op = op
        self.retry_after_s = retry_after_s
        super().__init__(
            f"control plane overloaded: {op!r} shed "
            f"(retry after ~{retry_after_s:g}s)")


class StorageDegradedError(RayTpuError):
    """Local storage (spill disk) cannot absorb an object right now.

    Typed retriable pushback for the spill degradation ladder: an
    ENOSPC/EIO spill failure under memory pressure retains the object
    in memory and backpressures the put instead of failing tasks; only
    a put that exhausts the whole backpressure budget surfaces this —
    and it still carries ``Retry-After`` so callers can keep backing
    off rather than treating the node as broken."""

    def __init__(self, detail: str, retry_after_s: float = 1.0):
        self.detail = detail
        self.retry_after_s = retry_after_s
        super().__init__(
            f"storage degraded: {detail} "
            f"(retry after ~{retry_after_s:g}s)")


class CheckpointWriteError(RayTpuError):
    """A checkpoint commit failed durably (ENOSPC/EIO in the staging or
    replace dance).  The previous checkpoint is intact and loadable —
    the manager rolls the dance back before raising — so callers keep
    training and retry the save later instead of aborting the run."""

    def __init__(self, name: str, detail: str):
        self.name = name
        self.detail = detail
        super().__init__(
            f"checkpoint {name!r} write failed ({detail}); "
            f"previous checkpoint kept")


class WalWriteError(RayTpuError):
    """The controller WAL hit an unrecoverable write/fsync failure.

    fsyncgate bug class: after ONE failed fsync the page-cache state of
    the log is unknowable, so the store poisons itself (every later
    append raises this) and the leader must self-fence and hand off to
    the HA standby rather than ack mutations it cannot persist."""

    def __init__(self, op: str, detail: str):
        self.op = op
        self.detail = detail
        super().__init__(f"controller WAL poisoned at {op!r}: {detail}")


class FunctionUnavailableError(RayTpuError):
    """A registered function's payload is gone from the object plane.

    Oversized function blobs live behind a kvref marker (the KV holds
    only a pointer); if the blob was evicted or its host died, the
    fetch fails AFTER registration succeeded.  Typed and retriable: the
    worker reports it in-band, the owning driver re-registers the blob
    and requeues the task without burning its retry budget."""

    def __init__(self, fid_hex: str, detail: str = ""):
        self.fid_hex = fid_hex
        self.detail = detail
        super().__init__(
            f"function {fid_hex[:12]} blob unavailable: {detail or 'lost'} "
            f"(owner re-registration required)")


class TaskCancelledError(RayTpuError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class TaskInterruptedByCancel(TaskCancelledError):
    """INTERNAL: the class injected by cancel_task's async-exception path.

    Distinguishes our injection from user code legitimately raising
    TaskCancelledError: if a reply carries THIS type for a task nobody
    cancelled, the interrupt landed in an innocent pool thread (the
    documented PyThreadState_SetAsyncExc race) and the driver re-queues
    the victim without consuming its retry budget."""
