"""User-facing error types (reference: python/ray/exceptions.py)."""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception; re-raised at every ``get`` of its returns.

    Mirrors the reference's RayTaskError: carries the remote traceback and,
    when picklable, the original exception as ``cause``.
    """

    def __init__(self, function_name: str, traceback_str: str,
                 cause: Exception | None = None):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(f"task {function_name} failed:\n{traceback_str}")


class ActorError(TaskError):
    pass


class ActorDiedError(RayTpuError):
    def __init__(self, actor_id_hex: str, reason: str):
        self.actor_id_hex = actor_id_hex
        self.reason = reason
        who = f"actor {actor_id_hex[:12]}" if actor_id_hex else "actor"
        super().__init__(f"{who} died: {reason}")


class ActorQuarantinedError(ActorDiedError):
    """An actor crash-looped into the QUARANTINED state.

    Raised to callers of an actor whose restarts exhausted the rolling
    restart window on poison-shaped deaths (crash-loop governance):
    distinguishes "this actor's own code keeps killing its worker" from
    plain ActorDiedError so callers stop resubmitting instead of
    retrying.  Subclasses ActorDiedError so replica routers and other
    existing handlers keep working.  The quarantine clears on TTL or
    ``ray-tpu quarantine clear`` (the actor then resumes RESTARTING)."""

    def __init__(self, actor_id_hex: str, reason: str):
        self._init_args = (actor_id_hex, reason)
        super().__init__(actor_id_hex, f"QUARANTINED (crash loop): {reason}")

    def __reduce__(self):
        # Exception.__reduce__ would replay the FORMATTED message into
        # __init__ — these errors cross process boundaries pickled, so
        # reconstruct from the original arguments instead
        return (self.__class__, self._init_args)


class WorkerCrashedError(RayTpuError):
    """The worker process executing a task died unexpectedly."""


class ObjectLostError(RayTpuError):
    def __init__(self, object_id_hex: str, detail: str = ""):
        super().__init__(f"object {object_id_hex[:16]} is lost: {detail}")


# A cross-node fetch that exhausted its retry / alternate-copy / relay
# ladder raises this typed error carrying every attempted source; it is
# defined next to the store client (the layer that fetches) and
# re-exported here as user-facing API.
from .core.object_store.client import ObjectFetchError  # noqa: E402,F401


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class ReplicaUnavailableError(RayTpuError):
    """A Serve deployment currently has zero live replicas.

    Typed fast-shed signal: the router raises it immediately instead of
    busy-polling its table until the request deadline, and the HTTP
    proxy maps it to 503 + ``Retry-After`` so load balancers back off
    instead of piling on a deployment that is restarting."""

    def __init__(self, deployment: str, retry_after_s: float = 1.0):
        self.deployment = deployment
        self.retry_after_s = retry_after_s
        super().__init__(
            f"no live replicas for deployment {deployment!r} "
            f"(retry after ~{retry_after_s:g}s)")


class ControlPlaneOverloadError(RayTpuError):
    """The controller shed a bulk-lane op under overload (brownout).

    Typed retriable pushback carrying ``Retry-After``: clients replay
    the op with full-jitter backoff until the controller's watermark
    state machine recovers; only a shed that outlives the whole
    failover/backoff budget surfaces as this exception."""

    def __init__(self, op: str, retry_after_s: float = 1.0):
        self.op = op
        self.retry_after_s = retry_after_s
        super().__init__(
            f"control plane overloaded: {op!r} shed "
            f"(retry after ~{retry_after_s:g}s)")


class StorageDegradedError(RayTpuError):
    """Local storage (spill disk) cannot absorb an object right now.

    Typed retriable pushback for the spill degradation ladder: an
    ENOSPC/EIO spill failure under memory pressure retains the object
    in memory and backpressures the put instead of failing tasks; only
    a put that exhausts the whole backpressure budget surfaces this —
    and it still carries ``Retry-After`` so callers can keep backing
    off rather than treating the node as broken."""

    def __init__(self, detail: str, retry_after_s: float = 1.0):
        self.detail = detail
        self.retry_after_s = retry_after_s
        super().__init__(
            f"storage degraded: {detail} "
            f"(retry after ~{retry_after_s:g}s)")


class CheckpointWriteError(RayTpuError):
    """A checkpoint commit failed durably (ENOSPC/EIO in the staging or
    replace dance).  The previous checkpoint is intact and loadable —
    the manager rolls the dance back before raising — so callers keep
    training and retry the save later instead of aborting the run."""

    def __init__(self, name: str, detail: str):
        self.name = name
        self.detail = detail
        super().__init__(
            f"checkpoint {name!r} write failed ({detail}); "
            f"previous checkpoint kept")


class WalWriteError(RayTpuError):
    """The controller WAL hit an unrecoverable write/fsync failure.

    fsyncgate bug class: after ONE failed fsync the page-cache state of
    the log is unknowable, so the store poisons itself (every later
    append raises this) and the leader must self-fence and hand off to
    the HA standby rather than ack mutations it cannot persist."""

    def __init__(self, op: str, detail: str):
        self.op = op
        self.detail = detail
        super().__init__(f"controller WAL poisoned at {op!r}: {detail}")


class FunctionUnavailableError(RayTpuError):
    """A registered function's payload is gone from the object plane.

    Oversized function blobs live behind a kvref marker (the KV holds
    only a pointer); if the blob was evicted or its host died, the
    fetch fails AFTER registration succeeded.  Typed and retriable: the
    worker reports it in-band, the owning driver re-registers the blob
    and requeues the task without burning its retry budget."""

    def __init__(self, fid_hex: str, detail: str = ""):
        self.fid_hex = fid_hex
        self.detail = detail
        super().__init__(
            f"function {fid_hex[:12]} blob unavailable: {detail or 'lost'} "
            f"(owner re-registration required)")


class PoisonTaskError(RayTpuError):
    """A task signature was quarantined after repeatedly killing workers.

    The controller's crash ledger counted ``poison_task_threshold``
    poison-shaped worker deaths (SIGSEGV, oom_kill, clean nonzero exit)
    for one task signature inside ``poison_window_s`` — across the
    crash-site anti-affinity spread, so a single bad host is ruled out —
    and fails further executions fast instead of burning more workers.
    ``evidence`` carries the trail: one ``{"node", "cause", "ts"}``
    entry per kill.  Clears on TTL expiry or ``ray-tpu quarantine
    clear``."""

    def __reduce__(self):
        # survive the pickle boundary with the evidence trail intact
        return (self.__class__, (self.signature, self.evidence,
                                 self.until))

    def __init__(self, signature: str, evidence=None, until: float = 0.0):
        self.signature = signature
        self.evidence = list(evidence or [])
        self.until = until
        nodes = sorted({e.get("node", "?")[:12] for e in self.evidence})
        causes = [f"{e.get('cause', '?')}@{e.get('node', '?')[:8]}"
                  for e in self.evidence]
        super().__init__(
            f"task signature {signature!r} quarantined as poison after "
            f"{len(self.evidence)} worker deaths on {len(nodes)} node(s) "
            f"{nodes}: {causes} (clears at TTL or `ray-tpu quarantine "
            f"clear`)")


class ReconstructionDepthError(RayTpuError):
    """Lineage reconstruction recursed past ``max_reconstruction_depth``.

    Typed replacement for the silent False at the depth check: the
    message names the oid lineage chain that was being walked, so the
    owner of a deep a->b->c->... pipeline sees WHERE the recursion blew
    the budget instead of a generic unreconstructable-object failure."""

    def __reduce__(self):
        return (self.__class__, (self.chain,))

    def __init__(self, chain):
        self.chain = [c.hex() if isinstance(c, bytes) else str(c)
                      for c in chain]
        shown = " -> ".join(c[:12] for c in self.chain)
        super().__init__(
            f"lineage reconstruction exceeded max_reconstruction_depth "
            f"({len(self.chain) - 1} levels deep) along oid chain "
            f"{shown}; raise RAY_TPU_MAX_RECONSTRUCTION_DEPTH or "
            f"checkpoint intermediate objects")


class TaskCancelledError(RayTpuError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class TaskInterruptedByCancel(TaskCancelledError):
    """INTERNAL: the class injected by cancel_task's async-exception path.

    Distinguishes our injection from user code legitimately raising
    TaskCancelledError: if a reply carries THIS type for a task nobody
    cancelled, the interrupt landed in an innocent pool thread (the
    documented PyThreadState_SetAsyncExc race) and the driver re-queues
    the victim without consuming its retry budget."""
