"""Developer tooling that ships with the package (linters, validators).

Nothing under here runs in the data/control plane — these are the
framework-invariant checks wired into tier-1 and `make lint`.
"""
