"""Rule: per-process lock-order cycles and awaits under a thread lock.

Two locks taken in opposite orders on two code paths is the classic
distributed-runtime deadlock: it never fires in tests (the windows are
microseconds) and freezes a nodelet in production.  With the shared
call graph, the order is statically visible:

* every ``with self.<lock>:`` / ``async with`` / bare ``.acquire()``
  on a lock attribute is an acquisition; while one is lexically held,
  any acquisition reached through the transitive self-call/module-call
  closure adds a ``held -> acquired`` edge;
* the per-module edge graph (each control-plane process is one module:
  controller, nodelet, worker runtime, engine) is searched for cycles —
  every strongly-connected component with more than one lock is a
  finding listing the contradictory sites;
* an ``await`` while a **threading** lock is held is the dynamic
  sibling of PR-13's loop-blocking rule: the coroutine parks, the OS
  lock stays taken, and every thread (and any other handler needing
  that lock) blocks behind a suspended frame.  ``asyncio`` primitives
  are exempt — parking while holding one is their design.

Lock identity is structural: ``self.<attr>`` assigned a
``threading.*``/``asyncio.*`` ``Lock/RLock/Condition/Semaphore``
factory (per class), or a module-level name assigned one.  Self-edges
(re-acquiring the same lock) are ignored — reentrant locks and
condition-variable idioms would drown the signal.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..engine import Finding, LintContext, Rule

_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore"}
_NESTED = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


class _FnLocks:
    __slots__ = ("direct", "held_calls", "edges")

    def __init__(self):
        #: lock id -> line of first direct acquisition in this function
        self.direct: Dict[str, int] = {}
        #: (held lock ids, callee (cls, name), line)
        self.held_calls: List[Tuple[Tuple[str, ...],
                                    Tuple[Optional[str], str], int]] = []
        #: direct lexical edges: (held, acquired) -> line
        self.edges: Dict[Tuple[str, str], int] = {}


class LockOrderRule(Rule):
    id = "lock-order"

    def visit_file(self, rel: str, tree: ast.AST, lines, ctx:
                   LintContext) -> List[Finding]:
        graph = ctx.graphs.get(rel)
        if graph is None:
            return []
        findings: List[Finding] = []
        # -- lock universe: per-class self attrs + module-level names
        class_locks: Dict[str, Dict[str, str]] = {}   # cls -> attr -> kind
        module_locks: Dict[str, str] = {}             # name -> kind
        for node in tree.body:
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                kind = self._factory_kind(node.value)
                if kind:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            module_locks[t.id] = kind
        for info in graph.iter_all():
            if info.cls is None:
                continue
            locks = class_locks.setdefault(info.cls, {})
            for sub in ast.walk(info.node):
                if isinstance(sub, ast.Assign) \
                        and isinstance(sub.value, ast.Call):
                    kind = self._factory_kind(sub.value)
                    if not kind:
                        continue
                    for t in sub.targets:
                        attr = self._self_attr(t)
                        if attr is not None:
                            locks[attr] = kind
        if not any(class_locks.values()) and not module_locks:
            return []

        # -- per-function lexical scan
        fn_locks: Dict[Tuple[Optional[str], str], _FnLocks] = {}
        kinds: Dict[str, str] = {}      # lock id -> thread|async
        for info in graph.iter_all():
            rec = _FnLocks()
            fn_locks[(info.cls, info.name)] = rec
            cl = class_locks.get(info.cls, {}) if info.cls else {}
            self._scan_fn(rel, info, cl, module_locks, rec, kinds,
                          findings)

        # -- propagate: edges from held-site into everything the callee
        #    closure acquires
        totals: Dict[Tuple[Optional[str], str], Dict[str, int]] = {}

        def total_acquires(key) -> Dict[str, int]:
            if key in totals:
                return totals[key]
            totals[key] = {}   # cycle guard
            info = graph.resolve(*key)
            if info is None:
                return totals[key]
            acc: Dict[str, int] = {}
            for fn in graph.closure(info):
                rec = fn_locks.get((fn.cls, fn.name))
                if rec:
                    for lk, ln in rec.direct.items():
                        acc.setdefault(lk, ln)
            totals[key] = acc
            return acc

        edges: Dict[Tuple[str, str], Tuple[int, str]] = {}
        for (cls, name), rec in fn_locks.items():
            scope = f"{cls}.{name}" if cls else name
            for (a, b), line in rec.edges.items():
                edges.setdefault((a, b), (line, scope))
            for held, callee, line in rec.held_calls:
                for b in total_acquires(callee):
                    for a in held:
                        if a != b:
                            edges.setdefault((a, b), (line, scope))

        findings.extend(self._cycle_findings(rel, edges))
        return findings

    # ------------------------------------------------------------- cycles
    def _cycle_findings(self, rel: str, edges) -> List[Finding]:
        adj: Dict[str, Set[str]] = {}
        for (a, b) in edges:
            adj.setdefault(a, set()).add(b)
        # strongly-connected components (iterative Tarjan would be
        # overkill: lock graphs are tiny — use reachability)
        reach: Dict[str, Set[str]] = {}

        def reachable(n: str) -> Set[str]:
            if n in reach:
                return reach[n]
            seen: Set[str] = set()
            stack = [n]
            while stack:
                cur = stack.pop()
                for nxt in adj.get(cur, ()):
                    if nxt not in seen:
                        seen.add(nxt)
                        stack.append(nxt)
            reach[n] = seen
            return seen

        nodes = sorted(adj)
        assigned: Set[str] = set()
        findings: List[Finding] = []
        for n in nodes:
            if n in assigned:
                continue
            # n is in its own SCC iff a cycle returns to it (reachable
            # is the strict forward set, so mutual membership already
            # implies the cycle)
            scc = {m for m in nodes
                   if m in reachable(n) and n in reachable(m)}
            if len(scc) < 2:
                continue
            assigned |= scc
            locks = sorted(scc)
            sites = []
            for (a, b), (line, scope) in sorted(edges.items()):
                if a in scc and b in scc:
                    sites.append(f"{a}->{b} at {scope}:{line}")
            first_line = min(line for (a, b), (line, _) in edges.items()
                             if a in scc and b in scc)
            findings.append(Finding(
                self.id, rel, first_line, "<module>",
                "<>".join(locks),
                f"lock-order cycle between {', '.join(locks)}: the "
                f"same locks are acquired in inconsistent order on "
                f"different paths ({'; '.join(sites[:4])}) — two "
                f"threads/tasks interleaving these paths deadlock; "
                f"pick one global order"))
        return findings

    # ------------------------------------------------------------ scanning
    def _scan_fn(self, rel, info, class_locks, module_locks, rec,
                 kinds, findings) -> None:
        cls = info.cls

        def lock_of(expr) -> Optional[str]:
            attr = self._self_attr(expr)
            if attr is not None and attr in class_locks:
                lid = f"{cls}.{attr}"
                kinds.setdefault(lid, class_locks[attr])
                return lid
            if isinstance(expr, ast.Name) and expr.id in module_locks:
                kinds.setdefault(expr.id, module_locks[expr.id])
                return expr.id
            return None

        def walk(node, held: Tuple[str, ...]):
            if isinstance(node, _NESTED) and node is not info.node:
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                new_held = list(held)
                for it in node.items:
                    ctx_expr = it.context_expr
                    # `with self._lock:` — possibly via `.acquire()`?
                    lk = lock_of(ctx_expr)
                    if lk is None:
                        walk(ctx_expr, tuple(new_held))
                    else:
                        for h in new_held:
                            if h != lk:
                                rec.edges.setdefault((h, lk),
                                                     node.lineno)
                        rec.direct.setdefault(lk, node.lineno)
                        new_held.append(lk)
                for child in node.body:
                    walk(child, tuple(new_held))
                return
            if isinstance(node, ast.Await):
                held_thread = [h for h in held
                               if kinds.get(h) == "thread"]
                if held_thread:
                    findings.append(Finding(
                        self.id, rel, node.lineno,
                        f"{cls}.{info.name}" if cls else info.name,
                        f"await-under:{held_thread[0]}",
                        f"`await` while holding threading lock "
                        f"{held_thread[0]} — the coroutine parks but "
                        f"the OS lock stays taken: every thread and "
                        f"handler needing it blocks behind a "
                        f"suspended frame; use an asyncio primitive "
                        f"or release before awaiting"))
                walk(node.value, held)
                return
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr == "acquire":
                    lk = lock_of(f.value)
                    if lk is not None:
                        for h in held:
                            if h != lk:
                                rec.edges.setdefault((h, lk),
                                                     node.lineno)
                        rec.direct.setdefault(lk, node.lineno)
                if held and isinstance(f, ast.Attribute) \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id == "self":
                    rec.held_calls.append((held, (cls, f.attr),
                                           node.lineno))
                elif held and isinstance(f, ast.Name):
                    rec.held_calls.append((held, (None, f.id),
                                           node.lineno))
                for child in ast.iter_child_nodes(node):
                    walk(child, held)
                return
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        for stmt in info.node.body:
            walk(stmt, ())

    # ------------------------------------------------------------- helpers
    @staticmethod
    def _self_attr(node) -> Optional[str]:
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            return node.attr
        return None

    def _factory_kind(self, call: ast.Call) -> Optional[str]:
        dotted = self.dotted(call.func)
        base = dotted.split(".")[-1]
        if base not in _LOCK_FACTORIES:
            return None
        return "async" if dotted.startswith("asyncio.") else "thread"
