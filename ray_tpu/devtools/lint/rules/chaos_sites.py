"""Rule: chaos injection sites used in code == KNOWN_SITES registry.

`ray-tpu chaos validate` lints *plans* against
``fault_injection.KNOWN_SITES``, but nothing checked the *call sites*:
a typo'd site string at a ``fi.ACTIVE.point(...)`` threads a fault
point that no valid plan can ever arm (and validate would even reject
the plan that tries), while a registry entry whose call site was
refactored away keeps validating plans that can never fire.  This rule
closes both directions:

* every site-string literal passed to ``point`` / ``async_point`` /
  ``_chaos_site`` — or assigned to a ``*_SITE`` constant — must exist
  in ``KNOWN_SITES``;
* every ``KNOWN_SITES`` key must be used by at least one such call
  site (or ``*_SITE`` constant) somewhere in the package.

The registry is parsed from ``util/fault_injection.py``'s AST — the
linted tree is never imported.  When that file is absent from the walk
(fixture trees), the rule is silent.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from ..engine import Finding, LintContext, Rule

_REGISTRY_FILE_SUFFIX = "util/fault_injection.py"
_POINT_FUNCS = {"point", "async_point", "_chaos_site"}


class ChaosSiteDriftRule(Rule):
    id = "chaos-site-drift"

    def __init__(self) -> None:
        #: site -> (rel, line) of first use in code
        self.used: Dict[str, Tuple[str, int]] = {}
        #: registry keys -> (rel, line)
        self.known: Dict[str, Tuple[str, int]] = {}
        self.registry_rel: str = ""

    def visit_file(self, rel: str, tree: ast.AST, lines, ctx:
                   LintContext) -> List[Finding]:
        if rel.endswith(_REGISTRY_FILE_SUFFIX):
            self.registry_rel = rel
            self._harvest_registry(rel, tree)
            return []
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                fname = ""
                if isinstance(node.func, ast.Attribute):
                    fname = node.func.attr
                elif isinstance(node.func, ast.Name):
                    fname = node.func.id
                if fname in _POINT_FUNCS and node.args:
                    site = self.str_const(node.args[0])
                    if site is not None:
                        self.used.setdefault(site, (rel, node.lineno))
            elif isinstance(node, ast.Assign):
                # SNAPSHOT_SITE = "train.snapshot_put" style constants
                for t in node.targets:
                    if isinstance(t, ast.Name) \
                            and t.id.endswith("_SITE"):
                        site = self.str_const(node.value)
                        if site is not None:
                            self.used.setdefault(site,
                                                 (rel, node.lineno))
        return []

    def _harvest_registry(self, rel: str, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) \
                    and any(isinstance(t, ast.Name)
                            and t.id == "KNOWN_SITES"
                            for t in node.targets) \
                    and isinstance(node.value, ast.Dict):
                for k in node.value.keys:
                    site = self.str_const(k)
                    if site is not None:
                        self.known[site] = (rel, k.lineno)

    def finalize(self, ctx: LintContext) -> List[Finding]:
        if not self.known:
            return []  # registry not in this tree (fixture runs)
        findings: List[Finding] = []
        for site, (rel, line) in sorted(self.used.items()):
            if site not in self.known:
                findings.append(Finding(
                    self.id, rel, line, "<module>", site,
                    f"chaos site {site!r} is threaded through the "
                    f"code but missing from "
                    f"fault_injection.KNOWN_SITES — no plan can ever "
                    f"arm it (chaos validate rejects the site)"))
        for site, (rel, line) in sorted(self.known.items()):
            if site not in self.used:
                findings.append(Finding(
                    self.id, rel, line, "KNOWN_SITES", site,
                    f"KNOWN_SITES entry {site!r} has no injection "
                    f"point in the code — plans naming it validate "
                    f"but can never fire; prune it or restore the "
                    f"call site"))
        return findings
