"""Rule: cross-process RPC payload contracts (request, consumption, reply).

The rpc-surface rule (PR-13) proves every op string has a handler; this
rule proves the two sides agree on the PAYLOAD.  The wire protocol is
schemaless msgpack dicts, so a sender building ``{"oid": ...}`` while
the handler reads ``req["object_id"]`` fails only at runtime — as a
KeyError inside the controller, typically first observed under version
skew or HA failover replay.  Three checks per op:

* **missing required key** — the handler reads ``req["k"]`` (no
  default) but some sender's payload provably omits ``k``.  Sender key
  sets come from dict literals and tracked locals (``payload = {...}``
  plus later ``payload["k"] = ...`` adds); senders whose payload we
  cannot resolve contribute nothing.
* **dead wire bytes** — a key some sender ships that NO handler of the
  op ever reads (checked only when every handler's read set is closed,
  i.e. the request dict never escapes whole).  Underscore-prefixed keys
  (``_ha_epoch``) are protocol metadata consumed by generic layers and
  exempt.
* **reply-shape drift** — a caller reads ``reply["k"]`` but no return
  arm of the handler ever includes ``k`` (checked only when every
  return statement in the handler closure is a dict literal or a bare
  constant; ``reply.get`` probes and underscore meta keys are exempt —
  the HA gate injects ``_not_leader`` replies on every op).

Handlers are resolved through the same idioms the rpc-surface rule
harvests — registry loops (``getattr(self, "_h_" + name)``), literal
``register("op", self._m)``, handler dicts, ``@server.handler`` — and
their payload reads are followed interprocedurally through the shared
call graph when the handler passes the request dict to a helper
(``self._do_x(data)``).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..engine import Finding, LintContext, Rule

_FWD_DEPTH = 3          # how deep a payload dict is followed
_HARMLESS_BUILTINS = {"len", "bool", "type", "isinstance", "repr",
                      "str", "id", "print"}


class _Sender:
    __slots__ = ("rel", "line", "scope", "keys", "cond_keys", "closed",
                 "none_payload")

    def __init__(self, rel, line, scope):
        self.rel = rel
        self.line = line
        self.scope = scope
        self.keys: Set[str] = set()        # keys always present
        self.cond_keys: Set[str] = set()   # keys maybe present
        self.closed = False                # key set fully known
        self.none_payload = False          # call sent no payload at all


class _HandlerReads:
    """Consumption profile of one handler (merged over its payload
    forwarding closure)."""

    __slots__ = ("required", "optional", "written", "open_reads",
                 "reply_sets", "reply_open", "has_dict_reply")

    def __init__(self):
        self.required: Dict[str, int] = {}   # key -> line of req["k"]
        self.optional: Set[str] = set()
        self.written: Set[str] = set()
        self.open_reads = False
        self.reply_sets: List[Set[str]] = []
        self.reply_open = False
        self.has_dict_reply = False


class _ReplyRead:
    __slots__ = ("rel", "line", "scope", "key")

    def __init__(self, rel, line, scope, key):
        self.rel = rel
        self.line = line
        self.scope = scope
        self.key = key


class RpcPayloadContractRule(Rule):
    id = "rpc-payload-contract"

    def __init__(self) -> None:
        #: op -> list of (rel, class-or-None, func name) handler refs
        self.handlers: Dict[str, List[Tuple[str, Optional[str], str]]] = {}
        #: ops whose handler expression we could not resolve — skip
        self.unresolved_ops: Set[str] = set()
        self.senders: Dict[str, List[_Sender]] = {}
        self.reply_reads: Dict[str, List[_ReplyRead]] = {}

    # ---------------------------------------------------------------- visit
    def visit_file(self, rel: str, tree: ast.AST, lines, ctx:
                   LintContext) -> List[Finding]:
        self._scan_scope(rel, None, "<module>", tree)
        return []

    def _scan_scope(self, rel: str, cls: Optional[str], scope: str,
                    node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                self._scan_scope(rel, child.name, child.name, child)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                self._decorator_handlers(rel, cls, child)
                self._scan_function(rel, cls, child)
            else:
                self._scan_scope(rel, cls, scope, child)

    def _decorator_handlers(self, rel, cls, fn) -> None:
        for dec in fn.decorator_list:
            if isinstance(dec, ast.Call) \
                    and isinstance(dec.func, ast.Attribute) \
                    and dec.func.attr == "handler" and dec.args:
                op = self.str_const(dec.args[0])
                if op is not None:
                    self.handlers.setdefault(op, []).append(
                        (rel, cls, fn.name))

    # ------------------------------------------------------ per function
    def _scan_function(self, rel: str, cls: Optional[str], fn) -> None:
        scope = fn.name
        #: local var -> (always keys, cond keys, resolvable) for
        #: payload locals (`payload = {...}`; later subscript adds)
        locals_: Dict[str, List] = {}
        #: reply var name -> op
        reply_vars: Dict[str, str] = {}
        # ast.walk covers nested defs too: a send site inside a nested
        # callback is still attributed to this (named) scope
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                self._track_local(node, locals_)
                self._track_reply_var(node, reply_vars)
            elif isinstance(node, ast.For):
                self._maybe_registry_loop(rel, cls, node)
            elif isinstance(node, ast.Call):
                self._maybe_register(rel, cls, node)
            elif isinstance(node, ast.Subscript):
                self._track_local_add(node, locals_)
        # second pass: send sites (locals_ now complete) + reply reads
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                self._maybe_send(rel, scope, node, locals_)
            self._maybe_reply_read(rel, scope, node, reply_vars)

    # -- payload locals ---------------------------------------------------
    @staticmethod
    def _track_local(node: ast.Assign, locals_: Dict[str, List]) -> None:
        if len(node.targets) != 1 \
                or not isinstance(node.targets[0], ast.Name):
            return
        name = node.targets[0].id
        if isinstance(node.value, ast.Dict):
            keys, closed = _dict_keys(node.value)
            if name in locals_:
                locals_[name][2] = False  # reassigned: give up
            else:
                locals_[name] = [keys, set(), closed]
        elif name in locals_:
            locals_[name][2] = False      # rebound to something else

    @staticmethod
    def _track_local_add(node: ast.Subscript, locals_: Dict[str, List]) \
            -> None:
        # `payload["k"] = ...` anywhere in the function: the key is at
        # least conditionally present
        if isinstance(node.ctx, ast.Store) \
                and isinstance(node.value, ast.Name) \
                and node.value.id in locals_:
            key = RpcPayloadContractRule.str_const(node.slice)
            if key is not None:
                locals_[node.value.id][1].add(key)
            else:
                locals_[node.value.id][2] = False

    def _track_reply_var(self, node: ast.Assign,
                         reply_vars: Dict[str, str]) -> None:
        if len(node.targets) != 1 \
                or not isinstance(node.targets[0], ast.Name):
            return
        op = self._send_op(node.value)
        if op is not None:
            reply_vars[node.targets[0].id] = op

    @staticmethod
    def _send_op(expr) -> Optional[str]:
        """Op string if ``expr`` is (an Await of) ``*.call("op", ...)``."""
        if isinstance(expr, ast.Await):
            expr = expr.value
        if isinstance(expr, ast.Call) \
                and isinstance(expr.func, ast.Attribute) \
                and expr.func.attr == "call" and expr.args:
            return RpcPayloadContractRule.str_const(expr.args[0])
        return None

    # -- registrations ----------------------------------------------------
    def _maybe_registry_loop(self, rel: str, cls: Optional[str],
                             node: ast.For) -> None:
        """``for name in ("a", ...): s.register(name,
        [wrapper(...,] getattr(self, "_h_" + name) [)])``"""
        if not isinstance(node.target, ast.Name) \
                or not isinstance(node.iter, (ast.Tuple, ast.List)):
            return
        loop_var = node.target.id
        prefix = None
        registers = False
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr == "register" and sub.args \
                    and isinstance(sub.args[0], ast.Name) \
                    and sub.args[0].id == loop_var:
                registers = True
            p = _getattr_prefix(sub, loop_var)
            if p is not None:
                prefix = p
        if not registers:
            return
        for elt in node.iter.elts:
            op = self.str_const(elt)
            if op is None:
                continue
            if prefix is None:
                self.unresolved_ops.add(op)
            else:
                self.handlers.setdefault(op, []).append(
                    (rel, cls, prefix + op))

    def _maybe_register(self, rel: str, cls: Optional[str],
                        call: ast.Call) -> None:
        if not isinstance(call.func, ast.Attribute):
            return
        if call.func.attr == "register" and len(call.args) >= 2:
            op = self.str_const(call.args[0])
            if op is None:
                return
            ref = self._handler_ref(rel, cls, call.args[1])
            if ref is None:
                # opaque handler expression (lambda, partial, computed
                # getattr with a literal op): skip the op entirely
                self.unresolved_ops.add(op)
            else:
                self.handlers.setdefault(op, []).append(ref)

    @staticmethod
    def _handler_ref(rel, cls, expr) -> Optional[Tuple]:
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self":
            return (rel, cls, expr.attr)
        if isinstance(expr, ast.Name):
            return (rel, None, expr.id)
        return None

    # -- send sites -------------------------------------------------------
    def _maybe_send(self, rel: str, scope: str, call: ast.Call,
                    locals_: Dict[str, List]) -> None:
        func = call.func
        op = None
        payload = _OMITTED
        if isinstance(func, ast.Attribute) \
                and func.attr in ("call", "notify"):
            op = self.str_const(call.args[0]) if call.args else None
            if op is not None:
                payload = call.args[1] if len(call.args) > 1 else None
        elif isinstance(func, (ast.Attribute, ast.Name)):
            # wrapper idiom: first string-const positional is the op,
            # the next positional is the payload candidate
            tail = func.attr if isinstance(func, ast.Attribute) \
                else func.id
            low = tail.lower()
            if ("call" in low or "notify" in low) \
                    and tail not in ("call", "notify"):
                for i, a in enumerate(call.args):
                    s = self.str_const(a)
                    if s is not None:
                        op = s
                        payload = call.args[i + 1] \
                            if len(call.args) > i + 1 else None
                        break
        if op is None:
            return
        # keyword payloads (timeout=...) are not the payload
        sender = _Sender(rel, call.lineno, scope)
        if payload is _OMITTED or payload is None \
                or (isinstance(payload, ast.Constant)
                    and payload.value is None):
            sender.closed = True
            sender.none_payload = True
        elif isinstance(payload, ast.Dict):
            sender.keys, sender.closed = _dict_keys(payload)
        elif isinstance(payload, ast.Name) \
                and payload.id in locals_:
            keys, cond, resolvable = locals_[payload.id]
            if resolvable:
                sender.keys = set(keys)
                sender.cond_keys = set(cond)
                sender.closed = True
            else:
                return      # unknown payload — contributes nothing
        else:
            return          # computed payload — contributes nothing
        self.senders.setdefault(op, []).append(sender)

    # -- reply reads ------------------------------------------------------
    def _maybe_reply_read(self, rel: str, scope: str, node,
                          reply_vars: Dict[str, str]) -> None:
        # r["k"] where r was assigned from *.call("op", ...), or the
        # chained form (await conn.call("op", ...))["k"]
        if isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, ast.Load):
            key = self.str_const(node.slice)
            if key is None:
                return
            op = None
            if isinstance(node.value, ast.Name):
                op = reply_vars.get(node.value.id)
            else:
                op = self._send_op(node.value)
            if op is not None:
                self.reply_reads.setdefault(op, []).append(
                    _ReplyRead(rel, node.lineno, scope, key))

    # ------------------------------------------------------------ finalize
    def finalize(self, ctx: LintContext) -> List[Finding]:
        if not self.handlers:
            return []
        findings: List[Finding] = []
        profiles: Dict[str, List[_HandlerReads]] = {}
        for op, refs in self.handlers.items():
            if op in self.unresolved_ops:
                continue
            profs = []
            for rel, cls, name in refs:
                graph = ctx.graphs.get(rel)
                info = graph.resolve(cls, name) if graph else None
                if info is None:
                    profs = None
                    break
                prof = _HandlerReads()
                _analyze_handler(graph, info, prof, _FWD_DEPTH, set())
                profs.append(prof)
            if profs:
                profiles[op] = profs

        for op in sorted(profiles):
            profs = profiles[op]
            handler_rel, _, handler_name = self.handlers[op][0]
            # 1. required key missing from a provably-closed sender.
            # A key that is ALSO membership-checked / .get-probed /
            # written by the handler is guarded ("if 'k' in req:
            # req['k']") — not required on the wire.
            required: Dict[str, int] = {
                k: v for k, v in profs[0].required.items()
                if k not in profs[0].optional
                and k not in profs[0].written}
            for p in profs[1:]:
                required = {k: v for k, v in required.items()
                            if k in p.required and k not in p.optional
                            and k not in p.written}
            for sender in self.senders.get(op, ()):
                if not sender.closed:
                    continue
                present = sender.keys | sender.cond_keys
                for k in sorted(required):
                    if k in present:
                        continue
                    what = "no payload at all" if sender.none_payload \
                        else f"keys {sorted(present)}"
                    findings.append(Finding(
                        self.id, sender.rel, sender.line, sender.scope,
                        f"{op}.{k}",
                        f"sends RPC op {op!r} with {what} but the "
                        f"handler `{handler_name}` "
                        f"({handler_rel}) reads req[{k!r}] without a "
                        f"default — KeyError on the serving process "
                        f"(first seen under version skew or failover "
                        f"replay); send the key or make the handler "
                        f"read .get({k!r}, ...)"))
            # 2. dead wire bytes (all handlers' read sets closed)
            if all(not p.open_reads for p in profs):
                read: Set[str] = set()
                for p in profs:
                    read |= set(p.required) | p.optional | p.written
                for sender in self.senders.get(op, ()):
                    for k in sorted((sender.keys | sender.cond_keys)
                                    - read):
                        if k.startswith("_"):
                            continue   # protocol meta (_ha_epoch)
                        findings.append(Finding(
                            self.id, sender.rel, sender.line,
                            sender.scope, f"{op}.{k}:dead",
                            f"key {k!r} is sent with RPC op {op!r} "
                            f"but no handler ever reads it — dead "
                            f"wire bytes on every call (drop it, or "
                            f"consume it in `{handler_name}`)"))
            # 3. reply-shape drift (all handlers reply-closed)
            if all(not p.reply_open and p.has_dict_reply
                   for p in profs):
                reply_union: Set[str] = set()
                for p in profs:
                    for s in p.reply_sets:
                        reply_union |= s
                for rr in self.reply_reads.get(op, ()):
                    if rr.key.startswith("_") or rr.key in reply_union:
                        continue
                    findings.append(Finding(
                        self.id, rr.rel, rr.line, rr.scope,
                        f"{op}.{rr.key}:reply",
                        f"reads reply[{rr.key!r}] of RPC op {op!r} "
                        f"but no return arm of handler "
                        f"`{handler_name}` ({handler_rel}) includes "
                        f"that key — reply-shape drift (KeyError on "
                        f"the caller)"))
        return findings


#: sentinel distinguishing "no payload argument" from explicit None
_OMITTED = object()


def _dict_keys(d: ast.Dict) -> Tuple[Set[str], bool]:
    """(literal string keys, fully-known?) for a dict literal."""
    keys: Set[str] = set()
    closed = True
    for k in d.keys:
        if k is None:                     # **spread
            closed = False
            continue
        s = RpcPayloadContractRule.str_const(k)
        if s is None:
            closed = False
        else:
            keys.add(s)
    return keys, closed


def _getattr_prefix(node, loop_var: str) -> Optional[str]:
    """``getattr(self, "_h_" + name)`` -> "_h_" (either operand
    order)."""
    if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
            and node.func.id == "getattr" and len(node.args) >= 2):
        return None
    arg = node.args[1]
    if isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Add):
        for const, var in ((arg.left, arg.right), (arg.right, arg.left)):
            s = RpcPayloadContractRule.str_const(const)
            if s is not None and isinstance(var, ast.Name) \
                    and var.id == loop_var:
                return s
    return None


# ------------------------------------------------------- handler analysis

def _analyze_handler(graph, info, prof: _HandlerReads, depth: int,
                     seen: Set[Tuple]) -> None:
    """Fold ``info``'s consumption of its payload parameter into
    ``prof``, following the request dict through ``self.helper(data)``
    forwards via the shared call graph."""
    key = (info.cls, info.name)
    if key in seen:
        return
    seen.add(key)
    args = [a.arg for a in info.node.args.args]
    if not args:
        return
    param = args[-1]
    if param in ("self", "conn"):
        return
    forwards: List[Tuple[str, int, Optional[str]]] = []
    _scan_payload_use(info.node, param, prof, forwards, top=True)
    if depth <= 0:
        if forwards:
            prof.open_reads = True
        return
    for callee, pos, kwname in forwards:
        target = graph.resolve(info.cls, callee)
        if target is None:
            prof.open_reads = True
            continue
        t_args = [a.arg for a in target.node.args.args]
        t_param = None
        if kwname is not None:
            t_param = kwname if kwname in t_args else None
        else:
            idx = pos + (1 if t_args and t_args[0] == "self" else 0)
            if idx < len(t_args):
                t_param = t_args[idx]
        if t_param is None:
            prof.open_reads = True
            continue
        sub = _HandlerReads()
        fwd2: List[Tuple[str, int, Optional[str]]] = []
        _scan_payload_use(target.node, t_param, sub, fwd2, top=False)
        # recurse one level deeper through the callee's own forwards
        for c2, p2, kw2 in fwd2:
            t2 = graph.resolve(target.cls, c2)
            if t2 is None:
                sub.open_reads = True
                continue
            sub_seen = set(seen)
            saved = (sub.reply_sets, sub.reply_open, sub.has_dict_reply)
            _analyze_forward(graph, t2, p2, kw2, sub, depth - 2,
                             sub_seen)
            sub.reply_sets, sub.reply_open, sub.has_dict_reply = saved
        prof.required.update(
            {k: v for k, v in sub.required.items()
             if k not in prof.required})
        prof.optional |= sub.optional
        prof.written |= sub.written
        prof.open_reads = prof.open_reads or sub.open_reads


def _analyze_forward(graph, target, pos, kwname, prof, depth, seen) \
        -> None:
    t_args = [a.arg for a in target.node.args.args]
    t_param = None
    if kwname is not None:
        t_param = kwname if kwname in t_args else None
    else:
        idx = pos + (1 if t_args and t_args[0] == "self" else 0)
        if idx < len(t_args):
            t_param = t_args[idx]
    if t_param is None:
        prof.open_reads = True
        return
    fwd: List[Tuple[str, int, Optional[str]]] = []
    _scan_payload_use(target.node, t_param, prof, fwd, top=False)
    if fwd and depth <= 0:
        prof.open_reads = True


def _scan_payload_use(fn, param: str, prof: _HandlerReads,
                      forwards: List, top: bool) -> None:
    """One function body: where does ``param`` (the request dict) go?
    ``top`` controls whether return statements define the reply
    shape."""

    def is_param(node) -> bool:
        return isinstance(node, ast.Name) and node.id == param

    def scan(node, in_test=False):
        if node is None:
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not fn:
            # the dict does not change identity — reads inside nested
            # defs are still reads of the same payload
            for child in ast.iter_child_nodes(node):
                scan(child)
            return
        if isinstance(node, ast.Subscript) and is_param(node.value):
            key = RpcPayloadContractRule.str_const(node.slice)
            if key is None:
                prof.open_reads = True
            elif isinstance(node.ctx, ast.Load):
                prof.required.setdefault(key, node.lineno)
            elif isinstance(node.ctx, ast.Store):
                prof.written.add(key)
            else:
                prof.optional.add(key)
            scan(node.slice)
            return
        if isinstance(node, ast.Call):
            scan_call(node)
            return
        if isinstance(node, ast.Compare) and len(node.ops) == 1:
            if isinstance(node.ops[0], (ast.In, ast.NotIn)) \
                    and is_param(node.comparators[0]):
                k = RpcPayloadContractRule.str_const(node.left)
                if k is not None:
                    prof.optional.add(k)
                scan(node.left)
                return
            if isinstance(node.ops[0], (ast.Is, ast.IsNot, ast.Eq,
                                        ast.NotEq)):
                # `data is None` / truthiness probes read no keys
                if is_param(node.left) or any(
                        is_param(c) for c in node.comparators):
                    for c in [node.left] + node.comparators:
                        if not is_param(c):
                            scan(c)
                    return
        if isinstance(node, ast.Return):
            if top:
                v = node.value
                if v is None or (isinstance(v, ast.Constant)):
                    pass                      # bare/scalar: no keys
                elif isinstance(v, ast.Dict):
                    keys, closed = _dict_keys(v)
                    prof.reply_sets.append(keys)
                    prof.has_dict_reply = True
                    if not closed:
                        prof.reply_open = True
                else:
                    prof.reply_open = True
            if node.value is not None:
                if is_param(node.value):
                    prof.open_reads = True
                else:
                    scan(node.value)
            return
        if isinstance(node, (ast.If, ast.While)):
            scan(node.test, in_test=True)
            for child in node.body + getattr(node, "orelse", []):
                scan(child)
            return
        if isinstance(node, (ast.BoolOp, ast.UnaryOp)) and in_test:
            for child in ast.iter_child_nodes(node):
                scan(child, in_test=True)
            return
        if isinstance(node, ast.For) and is_param(node.iter):
            prof.open_reads = True
            scan(node.target)
            for child in node.body + node.orelse:
                scan(child)
            return
        if is_param(node) and not in_test:
            # any unrecognized appearance: aliasing, serialization,
            # container membership — the read set is no longer closed
            prof.open_reads = True
            return
        for child in ast.iter_child_nodes(node):
            scan(child)

    def scan_call(node: ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute) and is_param(f.value):
            k = RpcPayloadContractRule.str_const(node.args[0]) \
                if node.args else None
            if f.attr == "get":
                if k is None:
                    prof.open_reads = True
                else:
                    prof.optional.add(k)
            elif f.attr == "pop":
                if k is None:
                    prof.open_reads = True
                elif len(node.args) >= 2:
                    prof.optional.add(k)
                else:
                    prof.required.setdefault(k, node.lineno)
            elif f.attr == "setdefault":
                if k is None:
                    prof.open_reads = True
                else:
                    prof.optional.add(k)
                    prof.written.add(k)
            else:
                # .items()/.keys()/.values()/.copy()/.update(...):
                # the whole dict is on the table
                prof.open_reads = True
            for a in node.args[1:]:
                scan(a)
            for kw in node.keywords:
                scan(kw.value)
            return
        if isinstance(f, ast.Attribute) and isinstance(f.value,
                                                       ast.Name) \
                and f.value.id == "self":
            for i, a in enumerate(node.args):
                if is_param(a):
                    forwards.append((f.attr, i, None))
                else:
                    scan(a)
            for kw in node.keywords:
                if is_param(kw.value):
                    if kw.arg is None:
                        prof.open_reads = True     # self.m(**data)
                    else:
                        forwards.append((f.attr, -1, kw.arg))
                else:
                    scan(kw.value)
            return
        if isinstance(f, ast.Name) and f.id in _HARMLESS_BUILTINS:
            for a in node.args:
                if not is_param(a):
                    scan(a)
            return
        for a in node.args:
            if is_param(a):
                prof.open_reads = True
            else:
                scan(a)
        for kw in node.keywords:
            if is_param(kw.value):
                prof.open_reads = True
            else:
                scan(kw.value)
        scan(f)

    for stmt in fn.body:
        scan(stmt)
