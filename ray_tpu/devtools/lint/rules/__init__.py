"""Rule registry: one visitor plugin per framework invariant."""

from .chaos_sites import ChaosSiteDriftRule
from .lock_order import LockOrderRule
from .loop_blocking import LoopBlockingRule
from .rpc_payload import RpcPayloadContractRule
from .rpc_surface import RpcSurfaceRule
from .thread_race import ThreadRaceRule
from .wal_determinism import WalReplayDeterminismRule
from .wal_ops import WalOpCoverageRule

ALL_RULES = (LoopBlockingRule, ThreadRaceRule, ChaosSiteDriftRule,
             WalOpCoverageRule, RpcSurfaceRule,
             RpcPayloadContractRule, LockOrderRule,
             WalReplayDeterminismRule)


def make_rules(only=None):
    """Fresh rule instances (cross-file rules carry per-run state)."""
    rules = [cls() for cls in ALL_RULES]
    if only:
        want = set(only)
        rules = [r for r in rules if r.id in want]
    return rules
