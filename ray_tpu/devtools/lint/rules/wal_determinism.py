"""Rule: WAL replay (`persistence._apply`) must be deterministic.

The HA design (PR-8) and the reference's replicated-GCS assumption
(arXiv:1712.05889 §4.2) rest on one invariant: leader and standby fold
IDENTICAL table state from identical WAL records.  ``_apply`` runs at
different wall-clock times on different hosts — any nondeterminism
source inside it (or anything it transitively calls) silently forks
the replicas: a ``time.time()`` stamp, a ``uuid4`` id, an env read, or
iterating a ``set`` (whose order depends on hash seeding across
processes) all produce divergent state that no test compares and no
failover survives cleanly.

This rule takes the transitive call closure of ``_apply`` in
``core/persistence.py`` from the shared call graph (module-local —
helpers that replay arms call live in the same file by design) and
flags every reachable nondeterminism source:

* clocks: ``time.time``/``monotonic``/``perf_counter``/``*_ns``,
  ``datetime.now``/``utcnow``
* randomness: ``random.*``, ``uuid.*``, ``secrets.*``, ``os.urandom``
* environment reads: ``os.getenv``, ``os.environ[...]``/``.get``
* set iteration: ``for ... in`` over a set literal/comprehension or a
  ``set(...)``/``frozenset(...)`` call (dicts are insertion-ordered
  and fine; sets are not)

A legitimate use (e.g. a replay-progress log line) is suppressed at
the site; anything else is a real replica-divergence bug.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ..engine import Finding, LintContext, Rule

_PERSISTENCE_FILE_SUFFIX = "core/persistence.py"
_APPLY_FN = "_apply"

_NONDET_EXACT = {
    "time.time": "wall clock",
    "time.time_ns": "wall clock",
    "time.monotonic": "process-local clock",
    "time.monotonic_ns": "process-local clock",
    "time.perf_counter": "process-local clock",
    "time.perf_counter_ns": "process-local clock",
    "datetime.now": "wall clock",
    "datetime.utcnow": "wall clock",
    "datetime.datetime.now": "wall clock",
    "datetime.datetime.utcnow": "wall clock",
    "os.urandom": "entropy",
    "os.getenv": "environment read",
    "os.environ.get": "environment read",
}
_NONDET_PREFIXES = {
    "random.": "randomness",
    "uuid.": "randomness",
    "secrets.": "entropy",
}


class WalReplayDeterminismRule(Rule):
    id = "wal-replay-determinism"

    def visit_file(self, rel: str, tree: ast.AST, lines, ctx:
                   LintContext) -> List[Finding]:
        if not rel.endswith(_PERSISTENCE_FILE_SUFFIX):
            return []
        graph = ctx.graphs.get(rel)
        if graph is None:
            return []
        entry = graph.functions.get(_APPLY_FN)
        if entry is None:
            for methods in graph.classes.values():
                if _APPLY_FN in methods:
                    entry = methods[_APPLY_FN]
                    break
        if entry is None:
            return []
        findings: List[Finding] = []
        for fn in graph.closure(entry):
            self._scan_fn(rel, fn, findings)
        return findings

    def _scan_fn(self, rel: str, fn, findings: List[Finding]) -> None:
        scope = fn.qname
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Call):
                why = self._nondet_call(node)
                if why is not None:
                    detail, kind = why
                    findings.append(Finding(
                        self.id, rel, node.lineno, scope, detail,
                        f"`{detail}(...)` inside the replay closure "
                        f"of persistence._apply ({kind}) — leader and "
                        f"standby must fold IDENTICAL state from "
                        f"identical WAL records; derive the value "
                        f"from the record itself or move it out of "
                        f"replay"))
            elif isinstance(node, ast.Subscript):
                if self.dotted(node.value) == "os.environ":
                    findings.append(Finding(
                        self.id, rel, node.lineno, scope, "os.environ",
                        f"os.environ[...] inside the replay closure "
                        f"of persistence._apply (environment read) — "
                        f"replicas with different environments fold "
                        f"different state from the same WAL"))
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                if self._is_set_iter(node.iter):
                    findings.append(Finding(
                        self.id, rel, node.lineno, scope,
                        "set-iteration",
                        f"iterating a set inside the replay closure "
                        f"of persistence._apply — set order depends "
                        f"on per-process hash seeding, so two "
                        f"replicas replaying the same records can "
                        f"fold tables in different order; sort it or "
                        f"use a list/dict"))

    def _nondet_call(self, call: ast.Call) -> Optional[tuple]:
        dotted = self.dotted(call.func)
        if not dotted:
            return None
        # `import time as _time` is the repo's local-import idiom —
        # normalize the leading component's underscores away
        parts = dotted.split(".")
        norm = ".".join([parts[0].lstrip("_") or parts[0]] + parts[1:])
        kind = _NONDET_EXACT.get(norm)
        if kind is not None:
            return dotted, kind
        for prefix, k in _NONDET_PREFIXES.items():
            if norm.startswith(prefix):
                return dotted, k
        return None

    def _is_set_iter(self, it) -> bool:
        if isinstance(it, (ast.Set, ast.SetComp)):
            return True
        if isinstance(it, ast.Call):
            dotted = self.dotted(it.func)
            return dotted in ("set", "frozenset")
        return False
