"""Rule: every WAL op appended to ControllerStore replays in _apply.

The controller's durability story is snapshot + WAL; HA promotion and
same-host restart both rebuild the tables by replaying records through
``persistence._apply``.  ``_apply`` silently ignores unknown ops (by
design — forward compat), which means a NEW op string appended via
``Controller._p(...)`` / ``pstore.append(...)`` without a matching
replay arm persists bytes that do nothing: the mutation is durable on
disk and lost on every restart.  That failure is invisible until the
first failover.  This rule cross-checks:

* every op-string literal appended (``self._p("op", ...)``, any
  ``*.pstore.append("op", ...)``) has an ``op == "..."`` arm in
  ``_apply``;
* every ``_apply`` arm has at least one appender (a dead arm is
  usually a refactor leftover — or intentional compat, which belongs
  in the baseline with that reason).

Silent when ``core/persistence.py`` is absent from the walked tree
(fixture runs).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from ..engine import Finding, LintContext, Rule

_PERSISTENCE_FILE_SUFFIX = "core/persistence.py"
_APPEND_SUFFIXES = ("pstore.append",)


class WalOpCoverageRule(Rule):
    id = "wal-op-coverage"

    def __init__(self) -> None:
        self.appended: Dict[str, Tuple[str, int, str]] = {}
        self.arms: Dict[str, Tuple[str, int]] = {}
        self.saw_apply = False

    def visit_file(self, rel: str, tree: ast.AST, lines, ctx:
                   LintContext) -> List[Finding]:
        if rel.endswith(_PERSISTENCE_FILE_SUFFIX):
            self._harvest_arms(rel, tree)
        scope = "<module>"
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call):
                        self._maybe_append(rel, node.name, sub)
            elif isinstance(node, ast.Call):
                self._maybe_append(rel, scope, node)
        return []

    def _maybe_append(self, rel: str, scope: str, call: ast.Call) -> None:
        dotted = self.dotted(call.func)
        # `self._p(...)` is the controller's WAL shorthand — only count
        # it under core/ (train/gbdt.py has an unrelated `_p` helper);
        # `*.pstore.append(...)` is unambiguous anywhere
        is_append = (dotted.endswith("._p")
                     and ("core/" in rel or rel.startswith("core/"))) \
            or any(dotted.endswith(s) for s in _APPEND_SUFFIXES)
        if not is_append or not call.args:
            return
        op = self.str_const(call.args[0])
        if op is not None:
            self.appended.setdefault(op, (rel, call.lineno, scope))

    def _harvest_arms(self, rel: str, tree: ast.AST) -> None:
        apply_fn = None
        for node in ast.walk(tree):
            if isinstance(node, ast.FunctionDef) and node.name == "_apply":
                apply_fn = node
                break
        if apply_fn is None:
            return
        self.saw_apply = True
        for node in ast.walk(apply_fn):
            if isinstance(node, ast.Compare) \
                    and isinstance(node.left, ast.Name) \
                    and node.left.id == "op" \
                    and len(node.ops) == 1 \
                    and isinstance(node.ops[0], (ast.Eq, ast.In)):
                for cmp in node.comparators:
                    consts = [cmp] if not isinstance(
                        cmp, (ast.Tuple, ast.List, ast.Set)) \
                        else list(cmp.elts)
                    for c in consts:
                        opname = self.str_const(c)
                        if opname is not None:
                            self.arms.setdefault(opname,
                                                 (rel, c.lineno))

    def finalize(self, ctx: LintContext) -> List[Finding]:
        if not self.saw_apply:
            return []
        findings: List[Finding] = []
        for op, (rel, line, scope) in sorted(self.appended.items()):
            if op not in self.arms:
                findings.append(Finding(
                    self.id, rel, line, scope, op,
                    f"WAL op {op!r} is appended here but has no "
                    f"replay arm in persistence._apply — the record "
                    f"is durable on disk and silently dropped on "
                    f"every restart/HA promotion"))
        for op, (rel, line) in sorted(self.arms.items()):
            if op not in self.appended:
                findings.append(Finding(
                    self.id, rel, line, "_apply", op,
                    f"persistence._apply has a replay arm for "
                    f"{op!r} but nothing appends that op — dead arm "
                    f"(refactor leftover, or baseline it as "
                    f"intentional WAL compat)"))
        return findings
