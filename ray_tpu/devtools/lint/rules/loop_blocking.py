"""Rule: no blocking calls inside ``async def`` bodies.

The controller and every nodelet are single asyncio loops; one blocking
call in a handler stalls heartbeats, leases, WAL replication, and every
other handler behind it (the actor-scheduler busy-spin of PR 8 and the
565 ms ``wait_actor`` parks of SCALE_r06 are the measured cost).  This
rule walks every ``async def`` (skipping nested sync ``def``/``lambda``
bodies, which usually run off-loop via ``to_thread``/executors) and
flags:

* ``time.sleep`` — use ``asyncio.sleep``
* sync file I/O: builtin ``open``, ``os.fsync``/any ``.fsync()``
* blocking subprocess calls (``subprocess.run``/``Popen``/…)
* blocking socket construction (``socket.create_connection``)
* unbounded lock acquisition: a non-awaited ``.acquire()`` with no
  ``timeout=``/``blocking=False`` (an awaited ``asyncio.Lock.acquire``
  is fine)
* known-blocking ray_tpu helpers: ``self._p`` / ``*.pstore.append``
  (WAL append + fsync), ``spill.write_object``/``spill.delete_file``
  (sync disk), ``EventLoopThread.run`` via ``*._lt.run`` (cross-thread
  join — deadlock bait on the loop)
"""

from __future__ import annotations

import ast
from typing import List

from ..engine import Finding, LintContext, Rule

#: exact dotted-name matches
_BLOCKING_EXACT = {
    "time.sleep": "time.sleep() blocks the event loop; use "
                  "`await asyncio.sleep(...)`",
    "open": "sync file I/O on the event loop; use "
            "`await asyncio.to_thread(...)` (or accept + baseline)",
    "os.fsync": "fsync on the event loop stalls every handler behind "
                "the disk",
    "subprocess.run": "blocking subprocess call on the event loop",
    "subprocess.call": "blocking subprocess call on the event loop",
    "subprocess.check_call": "blocking subprocess call on the event "
                             "loop",
    "subprocess.check_output": "blocking subprocess call on the event "
                               "loop",
    "subprocess.Popen": "fork/exec on the event loop (milliseconds "
                        "under load); prefer to_thread or the zygote "
                        "path",
    "socket.create_connection": "blocking connect on the event loop; "
                                "use asyncio.open_connection",
}

#: dotted-name suffix matches (obj resolved or not)
_BLOCKING_SUFFIX = {
    ".fsync": "fsync on the event loop stalls every handler behind "
              "the disk",
    "._p": "WAL append (+fsync) runs synchronously on the controller "
           "loop",
    ".pstore.append": "WAL append (+fsync) runs synchronously on the "
                      "controller loop",
    "._lt.run": "cross-thread join back into an event loop; "
                "deadlocks if called from that loop",
    "spill.write_object": "sync disk write on the event loop; wrap in "
                          "asyncio.to_thread",
    "spill.delete_file": "sync disk unlink on the event loop; wrap in "
                         "asyncio.to_thread",
}


def _short(dotted: str) -> str:
    return dotted.lstrip("?.") or "?"


class LoopBlockingRule(Rule):
    id = "loop-blocking"

    def visit_file(self, rel: str, tree: ast.AST, lines, ctx:
                   LintContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(tree):
            if isinstance(node, ast.AsyncFunctionDef):
                scope = node.name
                self._scan_async_body(rel, scope, node.body, findings)
        return findings

    # ------------------------------------------------------------ internals
    def _scan_async_body(self, rel: str, scope: str, body, findings,
                         awaited_calls=None) -> None:
        for stmt in body:
            self._scan_node(rel, scope, stmt, findings, awaited=False)

    def _scan_node(self, rel: str, scope: str, node: ast.AST, findings,
                   awaited: bool) -> None:
        # nested sync defs / lambdas usually execute off-loop
        # (to_thread, executors, callbacks) — skip their bodies; a
        # nested *async* def is picked up by visit_file's own walk
        # under its own scope name
        if isinstance(node, (ast.FunctionDef, ast.Lambda,
                             ast.AsyncFunctionDef)):
            return
        if isinstance(node, ast.Await):
            self._scan_node(rel, scope, node.value, findings,
                            awaited=True)
            return
        if isinstance(node, ast.Call):
            self._check_call(rel, scope, node, findings, awaited)
            # calls composed into an awaited wrapper (e.g. `await
            # asyncio.wait_for(lock.acquire(), ...)`) inherit the await
            for child in ast.iter_child_nodes(node):
                self._scan_node(rel, scope, child, findings,
                                awaited=awaited)
            return
        for child in ast.iter_child_nodes(node):
            self._scan_node(rel, scope, child, findings, awaited=False)

    def _check_call(self, rel: str, scope: str, call: ast.Call,
                    findings, awaited: bool) -> None:
        dotted = self.dotted(call.func)
        if not dotted:
            return
        msg = _BLOCKING_EXACT.get(dotted)
        detail = dotted
        if msg is None:
            for suffix, m in _BLOCKING_SUFFIX.items():
                if dotted.endswith(suffix):
                    msg, detail = m, _short(suffix)
                    break
        if msg is None and dotted.endswith(".acquire") and not awaited:
            kwargs = {kw.arg for kw in call.keywords}
            has_bound = bool({"timeout", "blocking"} & kwargs) \
                or len(call.args) >= 1
            if not has_bound:
                msg = ("unbounded lock.acquire() on the event loop; "
                       "pass a timeout, use blocking=False, or take "
                       "the lock off-loop")
                detail = _short(dotted)
        if msg is None:
            return
        findings.append(Finding(
            self.id, rel, call.lineno, scope, detail,
            f"`{_short(dotted)}(...)` inside `async def {scope}`: "
            f"{msg}"))
