"""Rule: client-side RPC op strings <-> registered server handlers.

The control plane's wire protocol is stringly typed: a client does
``conn.call("kv_put", ...)`` and a server must have run
``server.register("kv_put", handler)``.  A typo on either side fails
only at runtime ("no handler for method"), and an orphaned handler
keeps an op name alive in ``dispatch_stats`` / attribution tables that
nothing can reach.  This rule cross-checks the whole package:

**Registrations** are harvested from
* the registry-loop idiom: ``for name in ("a", "b", ...):
  server.register(name, getattr(self, "_h_" + name))``
* literal ``*.register("op", fn)`` calls
* handler-dict wiring: ``handlers["op"] = fn`` (any name containing
  ``handlers``) and dict literals assigned to such names
* ``@server.handler("op")`` decorators

**Call sites** are literal first arguments of ``*.call("op", ...)`` /
``*.notify("op", ...)``.

Checks: every call-site op must be registered somewhere; every
registered op must appear at some call site — in the package, in the
tests tree, or in the C++ sources (both scanned as reachability
evidence).  Dynamic pubsub handlers (``pub:*`` / ``pub_batch``) are
exempt from reachability: their call side is computed
(``"pub:" + channel``).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from ..engine import Finding, LintContext, Rule

#: registered names exempt from the reachability check: dispatched via
#: computed strings ("pub:" + channel) or by the remote runtime itself
_REACH_EXEMPT_PREFIXES = ("pub:",)
_REACH_EXEMPT = {"pub_batch"}


class RpcSurfaceRule(Rule):
    id = "rpc-surface"

    def __init__(self) -> None:
        #: op -> (rel, line, scope) of first registration
        self.registered: Dict[str, Tuple[str, int, str]] = {}
        #: op -> (rel, line, scope) of first literal call site
        self.called: Dict[str, Tuple[str, int, str]] = {}
        #: weak reachability witnesses: string args (any position) of
        #: call-shaped wrappers (`_node_call(addr, "op")`,
        #: `self._notify_controller("op", ...)`) — enough to prove a
        #: handler reachable, too fuzzy to assert registration against
        self.wrapper_evidence: set = set()

    def visit_file(self, rel: str, tree: ast.AST, lines, ctx:
                   LintContext) -> List[Finding]:
        self._scan(rel, "<module>", tree)
        return []

    # ------------------------------------------------------------- harvest
    def _scan(self, rel: str, scope: str, node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                self._scan(rel, child.name, child)
                continue
            if isinstance(child, ast.ClassDef):
                self._scan(rel, child.name, child)
                continue
            self._visit(rel, scope, child)
            self._scan(rel, scope, child)

    def _visit(self, rel: str, scope: str, node: ast.AST) -> None:
        if isinstance(node, ast.For):
            self._maybe_registry_loop(rel, scope, node)
        elif isinstance(node, ast.Call):
            self._maybe_call(rel, scope, node)
        elif isinstance(node, ast.Assign):
            self._maybe_handler_assign(rel, scope, node)

    def _maybe_registry_loop(self, rel: str, scope: str,
                             node: ast.For) -> None:
        """``for name in ("a", "b"): server.register(name, ...)``"""
        if not isinstance(node.target, ast.Name) \
                or not isinstance(node.iter, (ast.Tuple, ast.List)):
            return
        loop_var = node.target.id
        registers = False
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr == "register" \
                    and sub.args \
                    and isinstance(sub.args[0], ast.Name) \
                    and sub.args[0].id == loop_var:
                registers = True
                break
        if not registers:
            return
        for elt in node.iter.elts:
            name = self.str_const(elt)
            if name is not None:
                self.registered.setdefault(name,
                                           (rel, elt.lineno, scope))

    def _maybe_call(self, rel: str, scope: str, call: ast.Call) -> None:
        if isinstance(call.func, ast.Name):
            if "call" in call.func.id.lower() \
                    or "notify" in call.func.id.lower():
                for arg in call.args:
                    s = self.str_const(arg)
                    if s is not None:
                        self.wrapper_evidence.add(s)
            return
        if not isinstance(call.func, ast.Attribute):
            return
        attr = call.func.attr
        if attr == "register" and call.args:
            name = self.str_const(call.args[0])
            if name is not None:
                self.registered.setdefault(name,
                                           (rel, call.lineno, scope))
            return
        if attr == "handler" and len(call.args) == 1:
            name = self.str_const(call.args[0])
            if name is not None:
                self.registered.setdefault(name,
                                           (rel, call.lineno, scope))
            return
        if attr in ("call", "notify") and call.args:
            name = self.str_const(call.args[0])
            if name is not None:
                self.called.setdefault(name, (rel, call.lineno, scope))
            return
        if "call" in attr.lower() or "notify" in attr.lower():
            for arg in call.args:
                s = self.str_const(arg)
                if s is not None:
                    self.wrapper_evidence.add(s)

    def _maybe_handler_assign(self, rel: str, scope: str,
                              node: ast.Assign) -> None:
        for t in node.targets:
            if isinstance(t, ast.Subscript) \
                    and "handlers" in self.dotted(t.value).lower():
                name = self.str_const(t.slice)
                if name is not None:
                    self.registered.setdefault(name,
                                               (rel, t.lineno, scope))
            if isinstance(t, ast.Name) and "handlers" in t.id.lower() \
                    and isinstance(node.value, ast.Dict):
                for k in node.value.keys:
                    name = self.str_const(k)
                    if name is not None:
                        self.registered.setdefault(name,
                                                   (rel, k.lineno,
                                                    scope))

    # ------------------------------------------------------------ finalize
    def finalize(self, ctx: LintContext) -> List[Finding]:
        if not self.registered:
            return []   # no server surface in this tree (fixture runs)
        findings: List[Finding] = []
        for op, (rel, line, scope) in sorted(self.called.items()):
            if op not in self.registered:
                findings.append(Finding(
                    self.id, rel, line, scope, op,
                    f"RPC op {op!r} is sent here but no server "
                    f"registers a handler for it — the call can only "
                    f"ever raise 'no handler for method'"))
        for op, (rel, line, scope) in sorted(self.registered.items()):
            if op in _REACH_EXEMPT \
                    or op.startswith(_REACH_EXEMPT_PREFIXES):
                continue
            if op in self.called or op in self.wrapper_evidence \
                    or op in ctx.evidence:
                continue
            findings.append(Finding(
                self.id, rel, line, scope, op,
                f"registered RPC handler {op!r} has no call site in "
                f"the package, tests, or C++ sources — dead surface "
                f"(remove it, or baseline with the external caller "
                f"as the reason)"))
        return findings
