"""Rule: shared-state race heuristic for thread-spawning classes.

Classes like ``ContinuousBatchingEngine``, ``ElasticSnapshotter``, and
the HA replication machinery run a background thread over ``self``.
The contract that keeps them honest is simple: instance attributes the
thread mutates are either private to the thread or touched only under
the instance lock.  This rule checks it structurally:

* a class "spawns a thread" when any method constructs
  ``threading.Thread(target=self.<m>, ...)`` — ``<m>`` is the thread
  entry; the thread context is its transitive ``self.*()`` call
  closure within the class, taken from the engine's shared call graph
  (``LintContext.graphs`` — PR-14 generalized the closure this rule
  used to compute privately).
* "instance locks" are attributes assigned ``threading.Lock()`` /
  ``RLock()`` / ``Condition()`` (any dotted spelling).
* a mutation (``self.x = ...`` / ``self.x += ...``) counts as locked
  when lexically inside ``with self.<lock>:`` — or when the enclosing
  method's name ends in ``_locked`` (the repo convention for
  "caller holds the lock").
* FLAG an attribute that is mutated without the lock in the thread
  context while any public method (no leading underscore) also reads
  or writes it — and symmetrically, mutated without the lock in a
  public method while the thread context touches it.

``__init__`` is exempt (construction happens-before the thread).  This
is a heuristic: atomic-in-CPython counters and benign monotonic flags
will fire — suppress with ``# rtpu: allow[thread-race]`` at the
mutation site or baseline them with a reason.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..engine import Finding, LintContext, Rule

_LOCK_FACTORIES = ("Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore")
_EXEMPT_METHODS = {"__init__", "__new__", "__del__", "__init_subclass__"}


class _MethodInfo:
    def __init__(self, name: str, line: int):
        self.name = name
        self.line = line
        self.mutated_locked: Set[str] = set()
        self.mutated_unlocked: Dict[str, int] = {}   # attr -> line
        self.reads: Set[str] = set()


class ThreadRaceRule(Rule):
    id = "thread-race"

    def visit_file(self, rel: str, tree: ast.AST, lines, ctx:
                   LintContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(rel, node, ctx))
        return findings

    # ------------------------------------------------------------ per class
    def _check_class(self, rel: str, cls: ast.ClassDef,
                     ctx: LintContext) -> List[Finding]:
        methods: Dict[str, _MethodInfo] = {}
        lock_attrs: Set[str] = set()
        thread_targets: Set[str] = set()
        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            info = _MethodInfo(item.name, item.lineno)
            methods[item.name] = info
            self._scan_method(item, info, lock_attrs, thread_targets)
        if not thread_targets:
            return []

        # thread context: entry methods + transitive self-call closure
        # (from the engine's shared call graph)
        graph = ctx.graphs.get(rel)
        thread_ctx: Set[str] = graph.method_closure_names(
            cls.name, [m for m in thread_targets if m in methods]) \
            if graph is not None else set(thread_targets)
        thread_ctx &= set(methods)

        public = [m for m in methods
                  if not m.startswith("_") and m not in thread_ctx]
        findings: List[Finding] = []
        reported: Set[str] = set()
        for side_a, side_b, flip in ((thread_ctx, public, False),
                                     (public, thread_ctx, True)):
            for m in side_a:
                info = methods.get(m)
                if info is None or m in _EXEMPT_METHODS:
                    continue
                for attr, line in sorted(info.mutated_unlocked.items()):
                    if attr in reported:
                        continue
                    touched = [o for o in side_b
                               if o in methods and attr in
                               (methods[o].reads
                                | methods[o].mutated_locked
                                | set(methods[o].mutated_unlocked))]
                    if not touched:
                        continue
                    reported.add(attr)
                    who = "public method" if flip else "thread context"
                    other = ("thread context" if flip
                             else "public method(s)")
                    findings.append(Finding(
                        self.id, rel, line, f"{cls.name}.{m}", attr,
                        f"`self.{attr}` mutated in {who} "
                        f"`{cls.name}.{m}` without the instance lock "
                        f"({self._lock_hint(lock_attrs)}) while "
                        f"{other} {sorted(touched)} also touch it — "
                        f"take the lock, rename the method "
                        f"`*_locked` if the caller holds it, or "
                        f"suppress if the access is benign"))
        return findings

    @staticmethod
    def _lock_hint(lock_attrs: Set[str]) -> str:
        if lock_attrs:
            return "self." + " / self.".join(sorted(lock_attrs))
        return "no lock attribute found on this class"

    # ----------------------------------------------------------- per method
    def _scan_method(self, fn, info: _MethodInfo, lock_attrs: Set[str],
                     thread_targets: Set[str]) -> None:
        convention_locked = fn.name.endswith("_locked")
        self._scan_block(fn.body, info, lock_attrs, thread_targets,
                         locked=convention_locked)

    def _scan_block(self, body, info: _MethodInfo,
                    lock_attrs: Set[str], thread_targets: Set[str],
                    locked: bool) -> None:
        for node in body:
            self._scan_stmt(node, info, lock_attrs, thread_targets,
                            locked)

    def _scan_stmt(self, node, info, lock_attrs, thread_targets,
                   locked: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return  # nested scopes analyzed separately / skipped
        if isinstance(node, (ast.With, ast.AsyncWith)):
            takes_lock = any(self._is_self_lock(it.context_expr,
                                                lock_attrs)
                             for it in node.items)
            for it in node.items:
                self._scan_expr(it.context_expr, info, lock_attrs,
                                thread_targets)
            self._scan_block(node.body, info, lock_attrs,
                             thread_targets, locked or takes_lock)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                attr = self._self_attr(t)
                if attr is not None:
                    if self._is_lock_factory(getattr(node, "value",
                                                     None)):
                        lock_attrs.add(attr)
                    if locked:
                        info.mutated_locked.add(attr)
                    else:
                        info.mutated_unlocked.setdefault(attr,
                                                         t.lineno)
            value = getattr(node, "value", None)
            if value is not None:
                self._scan_expr(value, info, lock_attrs,
                                thread_targets)
            if isinstance(node, ast.AugAssign):
                # `self.x += 1` also reads self.x — already recorded
                # as a mutation, which is the stronger fact
                pass
            return
        # generic: record reads + self-calls, then recurse statements
        # (except handlers / match cases are statement containers too)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.stmt, ast.excepthandler)) \
                    or child.__class__.__name__ == "match_case":
                self._scan_stmt(child, info, lock_attrs,
                                thread_targets, locked)
            else:
                self._scan_expr(child, info, lock_attrs,
                                thread_targets)

    def _scan_expr(self, node, info, lock_attrs, thread_targets) -> None:
        if node is None or isinstance(node, (ast.FunctionDef,
                                             ast.AsyncFunctionDef,
                                             ast.Lambda,
                                             ast.ClassDef)):
            return
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                dotted = self.dotted(sub.func)
                if dotted.endswith("Thread") and "hread" in dotted:
                    for kw in sub.keywords:
                        if kw.arg == "target":
                            tgt = self._self_attr(kw.value)
                            if tgt is not None:
                                thread_targets.add(tgt)
            attr = self._self_attr(sub)
            if attr is not None and isinstance(getattr(sub, "ctx",
                                                       None), ast.Load):
                info.reads.add(attr)

    # ------------------------------------------------------------- helpers
    @staticmethod
    def _self_attr(node) -> Optional[str]:
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            return node.attr
        return None

    def _is_self_lock(self, expr, lock_attrs: Set[str]) -> bool:
        attr = self._self_attr(expr)
        return attr is not None and attr in lock_attrs

    def _is_lock_factory(self, value) -> bool:
        if not isinstance(value, ast.Call):
            return False
        dotted = self.dotted(value.func)
        return dotted.split(".")[-1] in _LOCK_FACTORIES
