"""AST-based static-analysis suite for framework invariants.

The control plane is a set of asyncio loops plus a few background
threads, and every defect class that has cost a PR cycle — a handler
blocking the controller loop, a thread racing a public method on shared
state, a chaos site/WAL op/RPC op drifting out of its registry — is
statically detectable.  `ray-tpu lint` runs five rules over the package
source (no cluster, no imports of the linted code):

``loop-blocking``
    blocking calls (``time.sleep``, sync file I/O, ``fsync``, blocking
    subprocess/socket ops, unbounded ``lock.acquire``, known-blocking
    ray_tpu helpers) inside ``async def`` bodies — each one stalls an
    event loop that heartbeats, leases, and serves are sharing.
``thread-race``
    in classes that spawn ``threading.Thread`` onto one of their own
    methods: instance attributes mutated on the thread side without the
    instance lock while a public method also touches them.
``chaos-site-drift``
    every ``fault_injection`` site string used at an injection point
    exists in ``KNOWN_SITES`` and vice versa (plans were validated
    before; now call sites are too).
``wal-op-coverage``
    every op string appended to the controller WAL has a replay arm in
    ``persistence._apply`` (a new WAL op can never silently not replay
    after restart/HA promotion), and no replay arm is dead.
``rpc-surface``
    every client-side op string sent over ``core/rpc.py`` has a
    registered server handler somewhere, and every registered handler
    is reachable from some call site (package, tests, or C++ sources).

Suppression: append ``# rtpu: allow[<rule-id>]`` (comma list ok) to the
flagged line or the line above it.  Grandfathered findings live in the
committed ``baseline.json`` next to this module — every entry must
carry a non-empty ``reason``.  See ``engine.py`` for the walker and
``rules/`` for the per-rule visitors.
"""

from .engine import (BASELINE_FILENAME, Finding, LintResult,  # noqa: F401
                     default_baseline_path, load_baseline, run_lint)
from .rules import ALL_RULES, make_rules  # noqa: F401
