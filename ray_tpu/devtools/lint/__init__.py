"""AST-based static-analysis suite for framework invariants.

The control plane is a set of asyncio loops plus a few background
threads, and every defect class that has cost a PR cycle — a handler
blocking the controller loop, a thread racing a public method on shared
state, a chaos site/WAL op/RPC op drifting out of its registry, two
sides of an RPC disagreeing on payload keys, two locks taken in
opposite orders, a WAL replay arm reading a clock — is statically
detectable.  `ray-tpu lint` runs eight rules over the package source
(no cluster, no imports of the linted code); the interprocedural ones
share one call-graph/closure builder (``callgraph.py``, built once per
file by the engine):

``loop-blocking``
    blocking calls (``time.sleep``, sync file I/O, ``fsync``, blocking
    subprocess/socket ops, unbounded ``lock.acquire``, known-blocking
    ray_tpu helpers) inside ``async def`` bodies — each one stalls an
    event loop that heartbeats, leases, and serves are sharing.
``thread-race``
    in classes that spawn ``threading.Thread`` onto one of their own
    methods: instance attributes mutated on the thread side without the
    instance lock while a public method also touches them.
``chaos-site-drift``
    every ``fault_injection`` site string used at an injection point
    exists in ``KNOWN_SITES`` and vice versa (plans were validated
    before; now call sites are too).
``wal-op-coverage``
    every op string appended to the controller WAL has a replay arm in
    ``persistence._apply`` (a new WAL op can never silently not replay
    after restart/HA promotion), and no replay arm is dead.
``rpc-surface``
    every client-side op string sent over ``core/rpc.py`` has a
    registered server handler somewhere, and every registered handler
    is reachable from some call site (package, tests, or C++ sources).
``rpc-payload-contract``
    per RPC op: the keys each sender provably ships vs the keys the
    handler reads (required ``req["k"]`` reads a sender omits →
    KeyError under version skew/failover replay; keys sent but never
    read → dead wire bytes; reply keys a caller reads that no return
    arm includes → reply-shape drift).
``lock-order``
    per-process lock-acquisition graph over the call closure: cycles
    between locks taken in inconsistent order (the silent deadlock),
    and ``await`` while holding a ``threading`` lock (the dynamic
    sibling of loop-blocking).
``wal-replay-determinism``
    no clocks, randomness, env reads, or set iteration inside the
    transitive closure of ``persistence._apply`` — leader and standby
    must fold identical state from identical WAL records.

Suppression: append ``# rtpu: allow[<rule-id>]`` (comma list ok) to the
flagged line or the line above it.  Grandfathered findings live in the
committed ``baseline.json`` next to this module — every entry must
carry a non-empty ``reason``; entries that stop firing FAIL the run
until pruned (or regenerate with ``ray-tpu lint --update-baseline``).
See ``engine.py`` for the walker, ``callgraph.py`` for the shared
closure builder, and ``rules/`` for the per-rule visitors.
"""

from .callgraph import (FuncInfo, ModuleGraph,  # noqa: F401
                        build_module_graph)
from .engine import (BASELINE_FILENAME, Finding, LintResult,  # noqa: F401
                     default_baseline_path, load_baseline, run_lint,
                     update_baseline)
from .rules import ALL_RULES, make_rules  # noqa: F401
