"""Lint engine: shared file walker, suppressions, baseline, output.

One parse per file: the walker builds each module's AST once and hands
it to every rule (``visit_file``); cross-file rules accumulate state in
the shared :class:`LintContext` and emit their findings in
``finalize``.  Nothing here imports the linted code — a file that
cannot even parse is itself reported as a finding.
"""

from __future__ import annotations

import ast
import fnmatch
import json
import os
import re
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set

from .callgraph import ModuleGraph, build_module_graph

BASELINE_FILENAME = "baseline.json"

#: directories never walked (bytecode, VCS, build junk)
_SKIP_DIRS = {"__pycache__", ".git", ".eggs", "build", "dist"}

#: inline suppression: ``# rtpu: allow[rule-a,rule-b]`` on the flagged
#: line or the line directly above it
_ALLOW_RE = re.compile(r"#\s*rtpu:\s*allow\[([A-Za-z0-9_,\- ]+)\]")

#: quoted identifiers harvested from evidence files (tests, C++
#: sources) — reachability witnesses for the rpc-surface rule
_EVIDENCE_STR_RE = re.compile(r'"([A-Za-z_][A-Za-z0-9_:.\-]*)"')


class Finding:
    """One lint finding.  ``key`` is line-number-free on purpose: it
    names the rule, file, enclosing scope, and a short detail token, so
    baseline entries survive unrelated edits to the same file."""

    def __init__(self, rule: str, rel: str, line: int, scope: str,
                 detail: str, message: str):
        self.rule = rule
        self.rel = rel
        self.line = line
        self.scope = scope
        self.detail = detail
        self.message = message

    @property
    def key(self) -> str:
        return f"{self.rule}:{self.rel}:{self.scope}:{self.detail}"

    def to_json(self) -> Dict[str, Any]:
        return {"rule": self.rule, "path": self.rel, "line": self.line,
                "scope": self.scope, "detail": self.detail,
                "key": self.key, "message": self.message}

    def __repr__(self) -> str:
        return f"<Finding {self.key} @{self.line}>"


class LintContext:
    """Shared state across files and rules for one lint run."""

    def __init__(self, root: str):
        self.root = root
        #: rel path -> source lines (rules may want the raw text)
        self.sources: Dict[str, List[str]] = {}
        #: rel path -> ModuleGraph (shared call-graph/closure builder:
        #: built once per file by the engine, reused by every
        #: interprocedural rule)
        self.graphs: Dict[str, ModuleGraph] = {}
        #: quoted strings seen in evidence files (tests, .cc/.h)
        self.evidence: Set[str] = set()
        #: free-form per-rule scratch space, keyed by rule id
        self.scratch: Dict[str, Any] = {}


class LintResult:
    def __init__(self) -> None:
        self.findings: List[Finding] = []      # new (fail the run)
        self.suppressed: List[Finding] = []    # inline-allowed
        self.baselined: List[Finding] = []     # grandfathered
        self.stale_baseline: List[str] = []    # baseline keys not seen
        self.baseline_errors: List[str] = []   # malformed entries
        self.files = 0
        self.duration_s = 0.0
        #: rule id -> seconds spent in its visit_file + finalize
        self.rule_timing: Dict[str, float] = {}

    @property
    def ok(self) -> bool:
        # stale baseline entries FAIL (PR-14): a key that no longer
        # fires means the code was fixed — prune the entry (or run
        # `ray-tpu lint --update-baseline`) so the baseline never
        # shadows a future regression at the same key
        return not self.findings and not self.baseline_errors \
            and not self.stale_baseline

    def to_json(self) -> Dict[str, Any]:
        return {
            "ok": self.ok,
            "files": self.files,
            "duration_s": round(self.duration_s, 3),
            "rule_timing": {r: round(t, 4)
                            for r, t in sorted(self.rule_timing.items())},
            "findings": [f.to_json() for f in self.findings],
            "suppressed": [f.to_json() for f in self.suppressed],
            "baselined": [f.to_json() for f in self.baselined],
            "stale_baseline": list(self.stale_baseline),
            "baseline_errors": list(self.baseline_errors),
        }


def default_baseline_path(package_dir: str) -> str:
    return os.path.join(package_dir, "devtools", "lint", BASELINE_FILENAME)


def load_baseline(path: str) -> tuple:
    """Returns ``(keys_to_reason, errors)``.  Every entry must carry a
    non-empty reason — a grandfathered finding without one is itself a
    lint failure (the baseline is documentation, not a mute button)."""
    if not path or not os.path.exists(path):
        return {}, []
    errors: List[str] = []
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        return {}, [f"baseline {path}: unreadable ({e})"]
    entries = data.get("entries") if isinstance(data, dict) else None
    if not isinstance(entries, list):
        return {}, [f"baseline {path}: expected {{'entries': [...]}}"]
    keys: Dict[str, str] = {}
    for i, ent in enumerate(entries):
        if not isinstance(ent, dict) or not ent.get("key"):
            errors.append(f"baseline entry #{i}: missing 'key'")
            continue
        reason = (ent.get("reason") or "").strip()
        if not reason:
            errors.append(f"baseline entry {ent['key']!r}: empty "
                          f"'reason' — every grandfathered finding "
                          f"must say why it is tolerated")
        keys[ent["key"]] = reason
    return keys, errors


def _walk_py(root: str) -> Iterable[str]:
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def _walk_evidence(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in _SKIP_DIRS)
            for fn in sorted(filenames):
                if fn.endswith((".py", ".cc", ".h", ".cpp")):
                    yield os.path.join(dirpath, fn)


def _allowed_rules(lines: List[str], line_no: int) -> Set[str]:
    """Suppressions in force at ``line_no`` (1-based): the line itself
    or the one above."""
    out: Set[str] = set()
    for idx in (line_no - 1, line_no - 2):
        if 0 <= idx < len(lines):
            m = _ALLOW_RE.search(lines[idx])
            if m:
                out.update(p.strip() for p in m.group(1).split(","))
    return out


def run_lint(package_dir: str, rules: Optional[Sequence] = None,
             baseline_path: Optional[str] = None,
             evidence_dirs: Sequence[str] = (),
             exclude: Sequence[str] = (),
             only_rel: Optional[Set[str]] = None) -> LintResult:
    """Lint every ``.py`` under ``package_dir`` with ``rules``.

    ``evidence_dirs`` (plus any C/C++ sources inside the package) are
    scanned for quoted strings only — reachability witnesses, never
    findings.  ``baseline_path=None`` means the committed default next
    to this module; pass ``""`` to disable the baseline entirely.
    ``exclude`` holds fnmatch patterns against the rel path.
    ``only_rel`` (the `--changed` path) still walks the WHOLE tree —
    cross-file rules need the full registries — but reports only
    findings anchored in those rel paths."""
    from .rules import make_rules
    t0 = time.monotonic()
    package_dir = os.path.abspath(package_dir)
    if rules is None:
        rules = make_rules()
    if baseline_path is None:
        baseline_path = default_baseline_path(package_dir)
    res = LintResult()
    ctx = LintContext(package_dir)

    # evidence pass: cheap textual harvest (no parse)
    cc_in_pkg = [package_dir]
    for path in _walk_evidence(list(evidence_dirs)):
        _harvest_evidence(path, ctx)
    for path in _walk_evidence(cc_in_pkg):
        if path.endswith((".cc", ".h", ".cpp")):
            _harvest_evidence(path, ctx)

    raw: List[Finding] = []
    for path in _walk_py(package_dir):
        rel = os.path.relpath(path, package_dir).replace(os.sep, "/")
        if any(fnmatch.fnmatch(rel, pat) for pat in exclude):
            continue
        res.files += 1
        try:
            with open(path, encoding="utf-8", errors="replace") as f:
                src = f.read()
            tree = ast.parse(src, filename=path)
        except SyntaxError as e:
            raw.append(Finding("parse-error", rel, e.lineno or 0,
                               "<module>", "syntax",
                               f"file does not parse: {e.msg}"))
            continue
        lines = src.splitlines()
        ctx.sources[rel] = lines
        ctx.graphs[rel] = build_module_graph(rel, tree)
        for rule in rules:
            rt0 = time.monotonic()
            raw.extend(rule.visit_file(rel, tree, lines, ctx) or ())
            res.rule_timing[rule.id] = \
                res.rule_timing.get(rule.id, 0.0) \
                + (time.monotonic() - rt0)
    for rule in rules:
        rt0 = time.monotonic()
        raw.extend(rule.finalize(ctx) or ())
        res.rule_timing[rule.id] = \
            res.rule_timing.get(rule.id, 0.0) + (time.monotonic() - rt0)

    # suppressions, dedupe (same key keeps its first site), baseline
    baseline, res.baseline_errors = load_baseline(baseline_path)
    seen_keys: Set[str] = set()
    hit_baseline: Set[str] = set()
    for f in raw:
        lines = ctx.sources.get(f.rel, [])
        if f.rule in _allowed_rules(lines, f.line):
            res.suppressed.append(f)
            continue
        if f.key in seen_keys:
            continue
        seen_keys.add(f.key)
        if f.key in baseline:
            hit_baseline.add(f.key)
            res.baselined.append(f)
        else:
            res.findings.append(f)
    res.stale_baseline = sorted(set(baseline) - hit_baseline)
    if only_rel is not None:
        res.findings = [f for f in res.findings if f.rel in only_rel]
    res.findings.sort(key=lambda f: (f.rel, f.line, f.rule))
    res.duration_s = time.monotonic() - t0
    return res


def _harvest_evidence(path: str, ctx: LintContext) -> None:
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            text = f.read()
    except OSError:
        return
    ctx.evidence.update(_EVIDENCE_STR_RE.findall(text))


def render_text(res: LintResult, verbose: bool = False) -> str:
    """Human-readable report (the `ray-tpu lint` default output)."""
    out: List[str] = []
    for f in res.findings:
        out.append(f"ERROR: {f.rel}:{f.line}: [{f.rule}] {f.message}")
        out.append(f"       key: {f.key}")
    for err in res.baseline_errors:
        out.append(f"ERROR: {err}")
    if verbose:
        for f in res.baselined:
            out.append(f"baselined: {f.rel}:{f.line}: [{f.rule}] "
                       f"{f.message}")
    for key in res.stale_baseline:
        out.append(f"ERROR: stale baseline entry (no longer fires): "
                   f"{key} — the code was fixed; prune the entry or "
                   f"run `ray-tpu lint --update-baseline`")
    status = "OK" if res.ok else f"{len(res.findings)} new finding(s)"
    out.append(f"{status}: {res.files} file(s) linted in "
               f"{res.duration_s:.2f}s — {len(res.findings)} new, "
               f"{len(res.baselined)} baselined, "
               f"{len(res.suppressed)} suppressed, "
               f"{len(res.stale_baseline)} stale")
    return "\n".join(out)


def update_baseline(path: str, res: LintResult) -> Dict[str, int]:
    """Regenerate the baseline file in place from ``res``: every
    finding that still fires keeps its existing reason, NEW findings
    get an EMPTY reason (which `ray-tpu lint` refuses until a human
    fills it in — regeneration documents, it does not absolve), and
    stale entries are dropped.  Returns counts for the CLI summary."""
    old, _ = load_baseline(path)
    entries: List[Dict[str, str]] = []
    kept = new = 0
    for f in sorted(res.baselined + res.findings, key=lambda f: f.key):
        reason = old.get(f.key, "")
        if reason:
            kept += 1
        else:
            new += 1
        entries.append({"key": f.key, "reason": reason})
    payload = {
        "version": 1,
        "comment": ("Grandfathered lint findings. Every entry needs a "
                    "non-empty reason; `ray-tpu lint` fails on new "
                    "findings not listed here. Remove entries as the "
                    "underlying code is fixed (stale entries FAIL). "
                    "Regenerate with `ray-tpu lint --update-baseline`."),
        "entries": entries,
    }
    tmp = path + ".tmp"
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    os.replace(tmp, path)
    return {"kept": kept, "new": new,
            "dropped": len(res.stale_baseline)}


# --------------------------------------------------------------- rule base

class Rule:
    """Base class for rule plugins.  Per-file rules override
    ``visit_file``; cross-file rules accumulate into ``ctx.scratch``
    and emit from ``finalize``."""

    id = "abstract"

    def visit_file(self, rel: str, tree: ast.AST, lines: List[str],
                   ctx: LintContext) -> List[Finding]:
        return []

    def finalize(self, ctx: LintContext) -> List[Finding]:
        return []

    # ---------------------------------------------------- shared helpers
    @staticmethod
    def dotted(node: ast.AST) -> str:
        """``a.b.c`` for a Name/Attribute chain, else ''."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(node.id)
            return ".".join(reversed(parts))
        if parts:
            # unresolvable base (call result, subscript): keep the
            # attribute tail so suffix matches still work
            return "?." + ".".join(reversed(parts))
        return ""

    @staticmethod
    def str_const(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        return None
