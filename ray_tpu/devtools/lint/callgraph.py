"""Shared call-graph / closure builder for the lint rules.

PR-13's thread-race rule privately computed a transitive ``self.*()``
call closure to decide what runs "on the thread side".  Every
interprocedural rule needs the same thing — the lock-order rule walks
what a method reaches while a lock is held, the WAL-determinism rule
walks what a replay arm can execute, the payload rule follows a request
dict handed to a helper.  This module builds that graph ONCE per file
(the engine stores it in ``LintContext.graphs``) and every rule shares
it.

Resolution is deliberately module-local and structural:

* ``self.m(...)`` resolves to method ``m`` of the lexically enclosing
  class (no MRO — the framework does not override control-plane
  methods across subclasses);
* a bare ``f(...)`` resolves to a module-level ``def f`` in the same
  file;
* anything else (other objects, imports, ``cls.m``) is out of graph —
  rules treat unresolved calls as opaque.

Nested ``def``/``lambda`` bodies are NOT folded into the enclosing
function's edges: a nested function is usually a callback/executor
payload that runs at a different time (often on a different thread or
loop), so attributing its calls to the enclosing frame would poison
both the race and the lock-order analyses.  Comprehension bodies DO
count (they run inline).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

_NESTED_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


class FuncInfo:
    """One function or method: its AST node plus resolved call edges."""

    __slots__ = ("rel", "cls", "name", "node", "lineno", "is_async",
                 "self_calls", "func_calls")

    def __init__(self, rel: str, cls: Optional[str], name: str, node):
        self.rel = rel
        self.cls = cls                       # class name or None
        self.name = name
        self.node = node
        self.lineno = node.lineno
        self.is_async = isinstance(node, ast.AsyncFunctionDef)
        #: method names called as ``self.<m>(...)`` (class scope)
        self.self_calls: Set[str] = set()
        #: bare names called as ``<f>(...)`` (module scope)
        self.func_calls: Set[str] = set()

    @property
    def qname(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name

    def __repr__(self) -> str:
        return f"<FuncInfo {self.rel}:{self.qname}>"


class ModuleGraph:
    """Call graph of one parsed module."""

    def __init__(self, rel: str):
        self.rel = rel
        #: class name -> {method name -> FuncInfo}
        self.classes: Dict[str, Dict[str, FuncInfo]] = {}
        #: module-level function name -> FuncInfo
        self.functions: Dict[str, FuncInfo] = {}
        self._closure_cache: Dict[Tuple[Optional[str], str],
                                  List[FuncInfo]] = {}

    # ------------------------------------------------------------ lookup
    def resolve(self, cls: Optional[str], name: str) -> Optional[FuncInfo]:
        if cls is not None:
            return self.classes.get(cls, {}).get(name)
        return self.functions.get(name)

    def iter_all(self) -> Iterable[FuncInfo]:
        for methods in self.classes.values():
            yield from methods.values()
        yield from self.functions.values()

    # ----------------------------------------------------------- closure
    def closure(self, fn: FuncInfo) -> List[FuncInfo]:
        """Transitive call closure of ``fn`` (including ``fn`` itself),
        following ``self.*`` edges within its class and bare-name edges
        to module functions.  Deterministic order (BFS, sorted
        frontier); cached per (class, name) — cycles are fine."""
        key = (fn.cls, fn.name)
        cached = self._closure_cache.get(key)
        if cached is not None:
            return cached
        seen: Set[Tuple[Optional[str], str]] = {key}
        order: List[FuncInfo] = [fn]
        frontier = [fn]
        while frontier:
            cur = frontier.pop(0)
            nxt: List[Tuple[Optional[str], str]] = []
            # self-calls stay in the CALLER's class context: a module
            # function has no self, so self_calls is empty there
            nxt.extend((cur.cls, m) for m in sorted(cur.self_calls))
            nxt.extend((None, f) for f in sorted(cur.func_calls))
            for ck, cn in nxt:
                if (ck, cn) in seen:
                    continue
                seen.add((ck, cn))
                info = self.resolve(ck, cn)
                if info is not None:
                    order.append(info)
                    frontier.append(info)
        self._closure_cache[key] = order
        return order

    def method_closure_names(self, cls: str, entries: Iterable[str]) \
            -> Set[str]:
        """Names of methods of ``cls`` reachable from ``entries`` via
        self-calls (the thread-race rule's historical contract)."""
        out: Set[str] = set()
        for entry in entries:
            info = self.resolve(cls, entry)
            if info is None:
                # e.g. a class nested inside a function (not in the
                # module-top-level graph): the entry itself still
                # counts as thread context
                out.add(entry)
                continue
            for fn in self.closure(info):
                if fn.cls == cls:
                    out.add(fn.name)
        return out


def build_module_graph(rel: str, tree: ast.AST) -> ModuleGraph:
    g = ModuleGraph(rel)
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            methods = g.classes.setdefault(node.name, {})
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    info = FuncInfo(rel, node.name, item.name, item)
                    _collect_edges(item, info)
                    methods.setdefault(item.name, info)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info = FuncInfo(rel, None, node.name, node)
            _collect_edges(node, info)
            g.functions.setdefault(node.name, info)
    return g


def _collect_edges(fn, info: FuncInfo) -> None:
    """Harvest call edges from ``fn``'s own body, skipping nested
    function/lambda scopes (they run at another time/place)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, _NESTED_SCOPES) \
                or isinstance(node, ast.ClassDef):
            continue
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id == "self":
                info.self_calls.add(f.attr)
            elif isinstance(f, ast.Name):
                info.func_calls.add(f.id)
        stack.extend(ast.iter_child_nodes(node))
