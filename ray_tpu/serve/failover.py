"""Transparent decode-stream failover: the proxy-side replay journal.

The continuous-batching engine (`decode_session.py`) pins a session's KV
cache to ONE replica — when that replica dies (chaos kill, node death)
or its node drains, the cache is gone.  But the routing layer driving
the stream has observed *every* emitted token, and greedy decode is
exactly deterministic: prompt + tokens-delivered-so-far fully determine
the rest of the stream.  So the proxy keeps a per-session **replay
journal** (prompt, emitted token ids, monotonic seq) and, on owner
failure, re-admits the session on a healthy replica with a
teacher-forced prefix prefill (``{"op": "resume"}``), resuming at the
next seq.  Resume IS chunked admission since PR-6: the target engine's
thread walks the replay prefix through the same fixed-shape chunk
programs every admission uses (``models.prefill_chunk_jit`` →
``models.cache_insert_slot``), so a resume never stalls the healthy
replica's live streams and never compiles a new program — and a
resume into a SPECULATING engine is byte-identical too, because greedy
speculative acceptance is exact-match against the target's own chain.
The client sees a stall — never an error, never a repeated or dropped
token.

Seq accounting makes the splice airtight:

* every engine reply stamps the seq of its first token; the journal
  length is the next seq the client expects;
* a reply overlapping the journal (a resume replayed after a partial
  read) is deduped by skipping the overlap;
* a reply AHEAD of the journal means a destructive ``next_chunk`` pop
  whose reply was lost in flight (proxy timeout, connection reset
  after the replica popped) — those tokens are unrecoverable from that
  session, so the gap triggers a resume, which regenerates them.

Failure classification:

* ``ReplicaUnavailableError`` from sid-sticky routing (owner out of the
  table) or a typed replica-death error → resume, reason
  ``replica_death``;
* a ``migrating`` reply (the owner's engine entered drain mode — the
  serve controller evacuating the replica before stopping it) → resume,
  reason ``drain``;
* any other request failure is retried on the same owner first (the
  session may be fine — e.g. an injected transient error); if it
  persists, or a seq gap is detected, resume with reason ``error``.

Chaos site ``serve.session_failover`` fires at the top of every
recovery attempt so the chaos suite can attack the failover path
itself.  Every migration counts
``ray_tpu_serve_sessions_migrated_total{reason}``, observes the
client-visible stall in ``ray_tpu_serve_session_failover_seconds``,
and records a ``serve_session_failover`` span.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

#: payload keys that are per-call transport details, not generation
#: parameters — everything else from the start payload is replayed
#: verbatim on resume so sampling-param-style extras survive failover
_NON_REPLAY_KEYS = ("op", "prompt", "generated", "sid", "max_tokens",
                    "timeout_s")


class StreamFailedError(RuntimeError):
    """Recovery exhausted: every resume attempt failed.  The SSE lane
    surfaces this as the in-band error event (the pre-failover
    behavior, now reserved for genuinely unrecoverable streams)."""


class FailoverSession:
    """One decode stream with transparent failover.

    ``call`` is the transport: ``call(payload: dict, sticky:
    Optional[str]) -> dict``, raising on RPC failure — the HTTP proxy
    passes a closure over its Router + ``call_with_retry``; tests pass
    scripted fakes.  The session itself is transport-agnostic and
    jax-free, so the journal/dedupe/resume logic is unit-testable
    without a cluster."""

    def __init__(self, call: Callable[..., Any], start_payload: Dict[str, Any],
                 *, deployment: str = "", attempts: Optional[int] = None,
                 failover_timeout_s: Optional[float] = None,
                 transient_retries: int = 2):
        self._call = call
        self._payload = dict(start_payload)
        self._name = deployment or "decode"
        self._attempts = attempts
        self._timeout = failover_timeout_s
        self._transient_retries = max(0, int(transient_retries))
        self.journal: List[int] = []   # every token delivered, in order
        self.sid: Optional[Any] = None
        self.chunked = False
        self.done = False
        self.failovers = 0
        self._sticky: Optional[str] = None
        self._migrate_pending = False

    # ------------------------------------------------------------- lifecycle

    def start(self) -> Any:
        """Issue the start op; returns the raw reply for the caller to
        emit.  Engine (``proto: "chunk"``) replies arm the journal;
        anything else (legacy core, error replies) passes through for
        the caller's fallback handling."""
        out = self._call(self._payload, None)
        if not isinstance(out, dict) or "error" in out:
            return out
        self.sid = out.get("sid")
        if out.get("proto") == "chunk":
            self.chunked = True
            self._sticky = self._owner_of(self.sid)
            self.journal.extend(out.get("token") or ())
            self.done = bool(out.get("done"))
        return out

    def next_tokens(self, max_tokens: int) -> Dict[str, Any]:
        """Fetch the next chunk, riding out owner death/drain/transient
        failures.  Returns ``{"tokens": [...], "done": bool}`` with
        journal-deduped tokens (possibly empty on a server-side wait
        timeout); raises :class:`StreamFailedError` only when recovery
        is exhausted."""
        transient_left = self._transient_retries
        while True:
            if self._migrate_pending:
                self._migrate_pending = False
                out = self._failover("drain")
            else:
                try:
                    out = self._call({"op": "next_chunk", "sid": self.sid,
                                      "max_tokens": max(1, int(max_tokens))},
                                     self._sticky)
                except Exception as e:   # noqa: BLE001
                    reason = self._death_reason(e)
                    if reason is not None:
                        out = self._failover(reason)
                    elif transient_left > 0:
                        # the session may be intact (injected error,
                        # blip): retry the same owner before resuming
                        transient_left -= 1
                        time.sleep(0.05)
                        continue
                    else:
                        out = self._failover("error")
            if not isinstance(out, dict):
                raise StreamFailedError(
                    f"protocol violation from {self._name}: {out!r}")
            if "error" in out:
                # unknown sid (engine restarted/evicted) or engine
                # failure: the journal can still replay it elsewhere
                out = self._failover("error")
            fresh = self._consume(out)
            if fresh is None:          # seq gap: tokens lost in flight
                out = self._failover("error")
                fresh = self._consume(out)
                if fresh is None:
                    raise StreamFailedError(
                        f"seq gap persisted across resume of "
                        f"{self._name} stream")
            if out.get("migrating") and not self.done:
                # buffered tokens delivered; owner is evacuating — line
                # up the resume before the next fetch
                self._migrate_pending = True
            if fresh or self.done:
                return {"tokens": fresh, "done": self.done}
            # empty non-terminal reply (server-side wait timeout or a
            # drain handoff with nothing buffered): loop — the migrate
            # flag above or the next poll makes progress

    def end(self) -> None:
        """Release the replica-side session; never raises (a dead owner
        has nothing to free)."""
        if self.sid is None:
            return
        try:
            self._call({"op": "end", "sid": self.sid}, self._sticky)
        except Exception:
            pass

    # -------------------------------------------------------------- internals

    @staticmethod
    def _owner_of(sid: Any) -> Optional[str]:
        """Engine sids are ``<replica_id>:<n>`` — the prefix pins every
        follow-up op to the owning replica."""
        if isinstance(sid, str) and ":" in sid:
            return sid.rsplit(":", 1)[0]
        return None

    @staticmethod
    def _death_reason(e: BaseException) -> Optional[str]:
        """Classify an RPC failure that kills the session outright."""
        from ..exceptions import ReplicaUnavailableError, TaskError
        from .handle import is_replica_down_error
        if is_replica_down_error(e):
            return "replica_death"
        if isinstance(e, ReplicaUnavailableError):
            return "replica_death"   # sticky owner out of the table
        if isinstance(e, TaskError) and isinstance(
                getattr(e, "cause", None), ReplicaUnavailableError):
            return "drain"           # owner shedding: engine draining
        return None

    def _consume(self, out: Dict[str, Any]) -> Optional[List[int]]:
        """Splice a reply into the journal by seq.  Returns the deduped
        fresh tokens, or None on a forward gap (lost destructive pop)."""
        toks = list(out.get("tokens") if out.get("tokens") is not None
                    else out.get("token") or ())
        seq = out.get("seq")
        if seq is None:
            seq = len(self.journal)    # legacy reply: trust ordering
        if seq > len(self.journal):
            return None
        fresh = toks[len(self.journal) - seq:]
        self.journal.extend(fresh)
        if out.get("done"):
            self.done = True
        return fresh

    def _failover(self, reason: str) -> Dict[str, Any]:
        """Re-admit the session on a healthy replica via teacher-forced
        replay of the journal; returns the resume reply (which carries
        the next token, seq-stamped at the journal length)."""
        from ..core.config import GlobalConfig
        from ..core.runtime_metrics import (SERVE_FAILOVER_LATENCY,
                                            SERVE_SESSIONS_MIGRATED)
        from ..util import fault_injection as fi
        from ..util import tracing
        from ..util.backoff import ExponentialBackoff
        t0 = time.time()
        if fi.ACTIVE is not None:
            act = fi.ACTIVE.point("serve.session_failover", self._name)
            if act is not None:
                if act["action"] in ("delay", "latency"):
                    time.sleep(max(0.0, act["delay_s"]))
                else:
                    raise StreamFailedError(
                        f"chaos: injected session_failover failure for "
                        f"{self._name}")
        attempts = max(1, self._attempts or
                       GlobalConfig.serve_session_failover_attempts)
        timeout = self._timeout if self._timeout is not None else \
            GlobalConfig.serve_session_failover_timeout_s
        # attempts is a FLOOR, the timeout a wall-clock budget for fast
        # rejections: while a dead node's replacement replica boots,
        # every resume sheds instantly with the typed 503 — counting
        # those against a small attempt budget would give up seconds
        # before the replacement comes up
        deadline = time.monotonic() + max(0.0, timeout)
        bo = ExponentialBackoff(base=0.05, cap=2.0)
        payload = {"op": "resume",
                   "prompt": list(self._payload.get("prompt") or ()),
                   "generated": list(self.journal)}
        payload.update({k: v for k, v in self._payload.items()
                        if k not in _NON_REPLAY_KEYS})
        last_err: Optional[BaseException] = None
        tries = 0
        while True:
            tries += 1
            try:
                out = self._call(payload, None)
            except Exception as e:   # noqa: BLE001
                last_err = e
                out = None
            if isinstance(out, dict) and "error" not in out \
                    and out.get("sid") is not None:
                self.sid = out["sid"]
                self._sticky = self._owner_of(self.sid)
                self.failovers += 1
                now = time.time()
                SERVE_SESSIONS_MIGRATED.inc(tags={"reason": reason})
                SERVE_FAILOVER_LATENCY.observe(
                    now - t0, {"deployment": self._name})
                tracing.record_span(
                    f"serve_session_failover::{self._name}", "serve",
                    t0, now, reason=reason, deployment=self._name,
                    resumed_at=len(self.journal), new_sid=str(self.sid))
                return out
            if out is not None:
                last_err = StreamFailedError(f"resume rejected: {out!r}")
            if tries >= attempts and time.monotonic() >= deadline:
                raise StreamFailedError(
                    f"decode-stream failover exhausted for {self._name} "
                    f"(reason={reason}, resumed_at={len(self.journal)}, "
                    f"tries={tries}): {last_err!r}") from last_err
            time.sleep(bo.next_delay())
