"""Model serving on the distributed runtime.

Capability mirror of the reference's `python/ray/serve/` (SURVEY.md §3.5:
controller actor reconciling replica actors, HTTP proxies, router with
in-flight-capped round robin, config push, `@serve.batch`, autoscaling).
TPU-first: a replica is a *program host* — it owns a local device mesh and
serves a pjit-compiled sharded model; scale-out replicates compiled
programs, scale-up grows one replica's mesh.
"""

from .api import (  # noqa: F401
    delete,
    get_deployment_handle,
    get_handle,
    http_address,
    list_deployments,
    proxy_statuses,
    run,
    shutdown,
    start,
    status_table,
)
from .schema import (  # noqa: F401
    DeployConfig,
    SchemaError,
    apply_config,
    get_deployed_config,
    load_config,
    status,
)
from .air_integrations import (  # noqa: F401
    PredictorDeployment,
    json_to_multi_ndarray,
    json_to_ndarray,
    ndarray_to_json,
    pandas_read_json,
)
from .batching import batch  # noqa: F401
from .autoscaler import Decision, FleetSample, ReplicaView  # noqa: F401
from .config import AutoscalingConfig, HTTPOptions  # noqa: F401
from .config import DecodeEngineConfig  # noqa: F401
from .prefix_cache import PrefixIndex  # noqa: F401
from .deployment import Deployment, deployment  # noqa: F401
from .failover import FailoverSession, StreamFailedError  # noqa: F401
from .ingress import ingress, route  # noqa: F401
from .replica import ReplicaContext, get_replica_context  # noqa: F401
from .gang import GangContext, get_gang_context  # noqa: F401
from .graph import composed, pipeline, run_graph  # noqa: F401
from .handle import ServeHandle  # noqa: F401
