"""HTTP ingress: aiohttp proxy actor.

Capability mirror of the reference's `HTTPProxy` ASGI actors
(`serve/_private/http_proxy.py:218,312,387`, managed per node by
`http_state.py:28`): prefix-routes requests to deployments through the
in-proc Router, JSON in/out.  The server runs on a dedicated event-loop
thread inside the replica-hosting worker process; replica calls execute on
a thread pool so the accept loop never blocks on inference.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
import uuid
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from ..util import tracing


def _push_latency(deployment: str, tenant: str, ttft_s: float,
                  itl) -> None:
    """Fire-and-forget one request's TTFT/ITL sample to this node's
    nodelet (``serve_metrics`` notify, the same lane the decode engine
    uses): the nodelet folds it into the tenant-labeled
    ``ray_tpu_serve_{ttft,itl}_seconds`` histograms and runs the SLO
    evaluator.  Proxy registries are never scraped — the fold is what
    makes per-tenant latency visible cluster-wide."""
    payload = {"deployment": deployment, "tenant": tenant,
               "ttft_s": round(float(ttft_s), 6),
               "itl_s": [round(float(v), 6) for v in itl]}
    try:
        from ..core.worker_runtime import current_worker_runtime
        rt = current_worker_runtime()
        if rt is not None and rt._loop is not None:
            asyncio.run_coroutine_threadsafe(
                rt.nodelet.notify("serve_metrics", payload), rt._loop)
    except Exception:
        pass   # driver-local proxy (tests) or torn-down runtime


class HTTPProxy:
    def __init__(self, controller_handle, host: str = "127.0.0.1",
                 port: int = 8000, node_id: Optional[str] = None):
        from .router import Router
        self._router = Router(controller_handle)
        self._controller = controller_handle
        self._host = host
        self._port = port
        self._pool = ThreadPoolExecutor(max_workers=32)
        self._ready = threading.Event()
        self._startup_error: Optional[str] = None
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        self._ready.wait(timeout=15.0)
        if self._startup_error:
            raise RuntimeError(self._startup_error)
        if node_id is not None:
            # PUSH the bound address to the controller (fire-and-forget):
            # the controller must never block waiting on a proxy, because
            # the proxy's own router calls back into the controller for
            # its first routing snapshot — a pull would deadlock.
            controller_handle.register_proxy.remote(node_id,
                                                    self.address())

    # -- server thread ------------------------------------------------------
    def _serve(self) -> None:
        try:
            from aiohttp import web
        except ImportError as e:  # pragma: no cover
            self._startup_error = f"aiohttp unavailable: {e}"
            self._ready.set()
            return

        from ..exceptions import ReplicaUnavailableError

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)

        def unavailable(e: ReplicaUnavailableError) -> "web.Response":
            # graceful degradation: zero live replicas sheds fast as 503
            # + Retry-After, so clients/load balancers back off instead
            # of stacking doomed requests on a restarting deployment
            return web.Response(
                status=503, text=str(e),
                headers={"Retry-After":
                         str(max(1, int(round(e.retry_after_s))))})

        def prefix_of(payload):
            """Prompt tokens of a session start/resume: the router's
            prefix-affinity key (sessions sharing a system prompt land
            where that prefix's KV is hot).  Resume includes generated
            tokens — its replay prefix is what the target must hold."""
            if not isinstance(payload, dict) or \
                    payload.get("op") not in ("start", "resume"):
                return None
            p = payload.get("prompt") or []
            if p and isinstance(p[0], (list, tuple)):
                if len(p) != 1:
                    return None   # batched prompts: no single prefix
                p = p[0]
            try:
                return [int(t) for t in p] + \
                    [int(t) for t in (payload.get("generated") or ())]
            except (TypeError, ValueError):
                return None

        def route_call(name, payload, sticky=None):
            from ..core.config import GlobalConfig
            from ..exceptions import TaskError
            from .handle import call_with_retry
            args = (payload,) if payload is not None else ()
            try:
                return call_with_retry(
                    self._router, name, args, {},
                    timeout_s=GlobalConfig.serve_request_timeout_s,
                    sticky_replica_id=sticky,
                    prefix_tokens=(None if sticky
                                   else prefix_of(payload)))
            except TaskError as e:
                # a replica-side typed shed (decode-engine admission
                # backpressure, draining engine) arrives wrapped as the
                # task error; unwrap so the 503 + Retry-After mapping —
                # and the failover client's classification — fire
                if isinstance(e.cause, ReplicaUnavailableError):
                    raise e.cause from None
                raise

        def make_call(name, payload, sticky=None):
            def call():
                return route_call(name, payload, sticky)
            return call

        async def stream_tokens(request, name, payload):
            """Server-sent-events generation (reference capability:
            Serve's StreamingResponse, serve/_private/http_util.py) —
            the PROXY drives a decode-session deployment
            (serve/decode_session.py protocol) and emits one SSE event
            per token, so clients get tokens as they decode instead of
            one request per token.

            Two transport lanes: replicas whose `start` reply announces
            ``proto: "chunk"`` (the continuous-batching engine) are
            drained via ``next_chunk`` — ONE sid-sticky router round
            trip per N buffered tokens — while legacy replicas fall back
            to one `next` RPC per token.  Either way the CLIENT contract
            is unchanged: one SSE event per token.

            The chunked lane rides a :class:`FailoverSession`
            (serve/failover.py): the proxy journals every emitted token,
            and an owner-replica death or drain mid-stream is healed by
            a teacher-forced resume on a healthy replica — the client
            sees a stall, never an error and never a duplicate/missing
            token.  A vanished CLIENT is cancelled eagerly: the loop
            checks the transport each chunk and releases the session
            instead of decoding to max_tokens into a full queue."""
            from ..core.config import GlobalConfig
            from .failover import FailoverSession
            max_new = int(payload.pop("max_new_tokens", 64))
            chunk = int(payload.pop("chunk_tokens", 0) or
                        GlobalConfig.serve_stream_chunk_tokens)
            # per-request tracing: the rid minted here rides the start
            # payload to the replica engine (underscore key = protocol
            # meta; FailoverSession replays it on resume, so a healed
            # stream keeps its id); the tenant — request field first,
            # x-tenant header second — labels the TTFT/ITL histograms,
            # cardinality-capped at the nodelet fold
            rid = uuid.uuid4().hex[:12]
            tenant = str(payload.pop("tenant", None)
                         or request.headers.get("x-tenant") or "anon")
            payload.setdefault("_rid", rid)
            t0 = time.time()
            ttft = None       # start-accepted -> first token ready
            itl = []          # gaps between consecutive SSE emissions

            def session_call(p, sticky=None):
                return route_call(name, p, sticky)

            sess = FailoverSession(session_call,
                                   {"op": "start", **payload},
                                   deployment=name)
            # the start op runs BEFORE headers go out: a failure here
            # still gets a clean HTTP 500/503 from the caller
            out = await loop.run_in_executor(self._pool, sess.start)
            sid = out.get("sid") if isinstance(out, dict) else None
            if isinstance(out, dict) and "error" not in out:
                ttft = time.time() - t0
            t_last = time.time()
            if isinstance(out, dict):
                out.pop("proto", None)
            resp = web.StreamResponse(headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache"})

            async def emit(obj):
                await resp.write(
                    b"data: " + json.dumps(obj).encode() + b"\n\n")

            def client_gone():
                t = request.transport
                return t is None or t.is_closing()

            # from here the session exists and this exchange IS the
            # response: prepare() itself can raise on a dead transport,
            # so it lives INSIDE the try — every exit path must release
            # the replica's KV cache, and unrecoverable mid-stream
            # failures become in-band error events (a second Response
            # on a live stream corrupts the connection)
            try:
                await resp.prepare(request)
                await emit(out)
                if sess.chunked and sid is not None \
                        and "error" not in out:
                    emitted = len(sess.journal)  # start carried token #1
                    while emitted < max_new and not sess.done:
                        if client_gone():
                            break   # client disconnected: cancel now
                        out = await loop.run_in_executor(
                            self._pool, sess.next_tokens,
                            min(chunk, max_new - emitted))
                        for tok in out["tokens"][:max_new - emitted]:
                            await emit({"token": [tok]})
                            emitted += 1
                            now = time.time()
                            itl.append(now - t_last)
                            t_last = now
                elif sid is not None and "error" not in out:
                    for _ in range(max_new - 1):
                        if client_gone():
                            break
                        out = await loop.run_in_executor(
                            self._pool,
                            make_call(name, {"op": "next", "sid": sid}))
                        await emit(out)
                        now = time.time()
                        itl.append(now - t_last)
                        t_last = now
                        if not isinstance(out, dict) \
                                or "error" in out or out.get("eos"):
                            break
            except Exception as e:
                try:
                    await emit({"error": str(e)})
                except Exception:
                    pass    # connection already gone
            finally:
                if sess.chunked:
                    await loop.run_in_executor(self._pool, sess.end)
                elif sid is not None:
                    try:
                        await loop.run_in_executor(
                            self._pool,
                            make_call(name, {"op": "end", "sid": sid}))
                    except Exception:
                        pass   # owner died mid-stream: nothing to free
            # request timeline span + one latency sample to the nodelet
            # fold — after the stream, off the token path
            try:
                tracing.record_span(
                    f"serve_request::{name}", "serve", t0, time.time(),
                    rid=rid, sid=sid, deployment=name, tenant=tenant,
                    tokens=(0 if ttft is None else 1 + len(itl)),
                    ttft_ms=(None if ttft is None
                             else round(ttft * 1e3, 3)))
            except Exception:
                pass
            if ttft is not None:
                _push_latency(name, tenant, ttft, itl)
            try:
                await resp.write(b"data: [DONE]\n\n")
                await resp.write_eof()
            except Exception:
                pass
            return resp

        async def handle(request: "web.Request") -> "web.Response":
            path = request.path
            if path == "/-/routes":
                return web.json_response(self._router.route_prefixes())
            if path == "/-/healthz":
                return web.Response(text="ok")
            full_path = path
            streaming = path.endswith("/stream")
            if streaming:
                path = path[:-len("/stream")]
            name = self._router.match_route(path)
            if name is None:
                # A request can beat the router's 0.25s poll TTL to a
                # just-deployed route (the table still holds the
                # boot-time snapshot); force one refresh before 404ing.
                # Costs one snapshot RPC, only on unmatched paths.
                self._router._refresh(force=True)
                name = self._router.match_route(path)
            if name is None:
                return web.Response(status=404,
                                    text=f"no deployment for {path}")
            info = self._router.route_info(name)
            ingress = info.get("ingress", False)
            if ingress and streaming:
                # the SSE decode-session lane is for token generators;
                # an ingress route ending in /stream is the
                # deployment's OWN route — re-match on the full path
                # and refresh the metadata (the re-match may land on a
                # DIFFERENT deployment than the stripped path did)
                streaming = False
                path = full_path
                name = self._router.match_route(path) or name
                info = self._router.route_info(name)
                ingress = info.get("ingress", False)
            if request.can_read_body:
                raw = await request.read()
                try:
                    payload = json.loads(raw) if raw else None
                except json.JSONDecodeError:
                    payload = raw.decode("utf-8", "replace")
            else:
                payload = None
            if ingress:
                # @serve.ingress: the deployment dispatches on the full
                # http context; body is the RAW decoded body only —
                # query params have their own field
                prefix = (info.get("route_prefix") or "/").rstrip("/")
                from .ingress import HTTP_KEY
                payload = {HTTP_KEY: {
                    "path": path[len(prefix):] or "/",
                    "method": request.method,
                    "query": dict(request.query),
                    "body": payload,
                }}
            elif payload is None and request.query:
                payload = dict(request.query)

            if streaming:
                if not isinstance(payload, dict):
                    return web.Response(
                        status=400,
                        text="/stream needs a JSON object body")
                try:
                    return await stream_tokens(request, name, payload)
                except ReplicaUnavailableError as e:
                    return unavailable(e)
                except Exception as e:
                    return web.Response(status=500, text=str(e))

            try:
                result = await loop.run_in_executor(
                    self._pool, make_call(name, payload))
            except ReplicaUnavailableError as e:
                return unavailable(e)
            except Exception as e:
                return web.Response(status=500, text=str(e))
            if isinstance(result, (bytes, bytearray)):
                return web.Response(body=bytes(result))
            if isinstance(result, str):
                return web.Response(text=result)
            if ingress and isinstance(result, dict) \
                    and isinstance(result.get("status"), int):
                # ingress dispatchers signal HTTP status via the
                # reserved key (404/405 must not read as 200 to load
                # balancers and monitors)
                return web.json_response(result,
                                         status=result["status"])
            return web.json_response(result)

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", handle)
        runner = web.AppRunner(app)

        async def start():
            await runner.setup()
            site = web.TCPSite(runner, self._host, self._port)
            try:
                await site.start()
                if self._port == 0:
                    # ephemeral bind (per-node proxies on one shared
                    # host): report the real port
                    self._port = site._server.sockets[0].getsockname()[1]
            except OSError as e:
                self._startup_error = str(e)
            self._ready.set()

        async def autoscale_ticker():
            """Periodic controller nudge: the autoscale loop must tick
            through idle valleys too (scale-down to min_replicas), and
            with zero traffic nothing else polls the controller.  The
            proxy is the natural host — one exists wherever Serve
            serves HTTP, and a fire-and-forget actor call per interval
            costs nothing."""
            from ..core.config import GlobalConfig
            while True:
                iv = GlobalConfig.serve_autoscale_interval_s
                if not iv or iv <= 0:
                    await asyncio.sleep(5.0)
                    continue
                await asyncio.sleep(max(0.25, float(iv)))
                try:
                    self._controller.autoscale_tick.remote()
                except Exception:
                    pass   # controller restarting: next tick retries

        loop.run_until_complete(start())
        if not self._startup_error:
            loop.create_task(autoscale_ticker())
            loop.run_forever()

    # -- actor surface ------------------------------------------------------
    def address(self) -> str:
        return f"http://{self._host}:{self._port}"

    def node_id(self) -> Optional[str]:
        """Node actually hosting this proxy (it may not be the node of
        whoever created it — HeadOnly spawns with no affinity)."""
        try:
            from .. import api
            return api.get_runtime_context().node_id
        except Exception:
            return None

    def healthy(self) -> bool:
        return self._thread.is_alive() and not self._startup_error
