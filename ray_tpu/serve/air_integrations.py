"""Serve an AIR Checkpoint as a deployment.

Capability mirror of the reference's `serve/air_integrations.py`
(`PredictorDeployment` at air_integrations.py:359 — load a
checkpointed model once per replica, serve predictions over HTTP with
request adapters) plus the `serve/http_adapters.py` role (map a raw
request payload to model input).

TPU-native shape: the predictor builder is the same
``predictor_fn(checkpoint) -> (batch -> predictions)`` contract used by
`ray_tpu.air.BatchPredictor`, so one builder serves both offline
(Dataset) and online (Serve) inference; replicas micro-batch through
``@serve.batch``, which is where TPU inference wants to live (one
compiled program over a stacked batch instead of per-request calls).
"""

from __future__ import annotations

from typing import Any, Callable

from ..air.checkpoint import Checkpoint
from .batching import batch
from .deployment import Deployment, deployment


def json_to_ndarray(payload: Any):
    """Default HTTP adapter: ``{"array": [...]}`` or a bare JSON list →
    numpy array (the reference's `http_adapters.json_to_ndarray`)."""
    import numpy as np
    if isinstance(payload, dict) and "array" in payload:
        return np.asarray(payload["array"])
    return np.asarray(payload)


def ndarray_to_json(pred: Any):
    """Default response adapter: arrays → JSON-serializable lists."""
    import numpy as np
    arr = np.asarray(pred)
    return arr.tolist() if arr.ndim else arr.item()


def json_to_multi_ndarray(payload: Any):
    """``{"col": {"array": [...]} | [...], ...}`` → dict of arrays
    (the reference's `http_adapters.json_to_multi_ndarray` — the
    multi-input model adapter)."""
    if not isinstance(payload, dict):
        raise TypeError("json_to_multi_ndarray expects a JSON object "
                        "mapping input names to arrays")
    return {k: json_to_ndarray(v) for k, v in payload.items()}


def pandas_read_json(payload: Any):
    """JSON records/columns → pandas DataFrame (the reference's
    `http_adapters.pandas_read_json` — the tabular-model adapter)."""
    import pandas as pd
    if isinstance(payload, list):
        return pd.DataFrame.from_records(payload)
    if isinstance(payload, dict):
        return pd.DataFrame(payload)
    raise TypeError("pandas_read_json expects JSON records (list of "
                    "objects) or a columns object")


def PredictorDeployment(
        checkpoint: Checkpoint,
        predictor_fn: Callable[[Checkpoint], Callable[[Any], Any]], *,
        name: str = "predictor",
        adapter: Callable[[Any], Any] = json_to_ndarray,
        response_adapter: Callable[[Any], Any] = ndarray_to_json,
        max_batch_size: int = 8,
        batch_wait_timeout_s: float = 0.01,
        **deployment_options: Any) -> Deployment:
    """Checkpoint + predictor builder → a ready-to-run Deployment.

    Each replica rebuilds the model from the checkpoint ONCE in its
    constructor; requests are adapted to model input, stacked into
    micro-batches, predicted in one call, and un-stacked into per-request
    responses.  ``deployment_options`` pass through to
    ``serve.deployment`` (num_replicas, autoscaling_config, gang_size,
    route_prefix, ...).

    Example::

        dep = PredictorDeployment(ckpt, BatchPredictor.from_sklearn(ckpt)
                                  .predictor_fn, num_replicas=2)
        handle = serve.run(dep, name="model")
        handle.remote([1.0, 2.0]).result()
    """
    ckpt_blob = checkpoint.to_dict()   # plain dict: ships in the actor

    @deployment(name=name, **deployment_options)
    class _Predictor:
        def __init__(self):
            self._predict = predictor_fn(Checkpoint.from_dict(ckpt_blob))

        @batch(max_batch_size=max_batch_size,
               batch_wait_timeout_s=batch_wait_timeout_s)
        def _predict_batch(self, items):
            import numpy as np
            # items are pre-adapted arrays; a ragged mix of valid shapes
            # still fails the whole micro-batch (stacked inference is the
            # point) — but malformed payloads were rejected per-request
            # in __call__ before ever reaching the batcher
            preds = self._predict(np.stack(items))
            return [response_adapter(p) for p in preds]

        def __call__(self, payload):
            import numpy as np
            arr = np.asarray(adapter(payload))
            # non-numeric payloads (object/str/datetime arrays) fail HERE,
            # never inside a micro-batch shared with valid requests
            if not (np.issubdtype(arr.dtype, np.number)
                    or arr.dtype == bool):
                raise ValueError(
                    f"adapter produced a non-numeric array "
                    f"(dtype {arr.dtype}) from payload of type "
                    f"{type(payload).__name__}")
            return self._predict_batch(arr)

    return _Predictor
