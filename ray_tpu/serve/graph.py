"""Model composition: multi-deployment inference graphs.

Capability mirror of the reference's deployment graphs
(/root/reference/python/ray/serve/deployment_graph.py + the DAGDriver in
serve/drivers.py, built on ray/dag): several deployments composed into one
routable endpoint.  Two entry points:

  * ``serve.pipeline([d1, d2, ...])`` — the linear chain (each stage's
    output feeds the next stage's input; the dominant production shape:
    preprocess → model → postprocess),
  * ``serve.composed(fn, deployments={...})`` — arbitrary composition:
    ``fn(handles, *args)`` runs inside a driver deployment with a handle
    per upstream deployment, so branches/ensembles/conditionals are plain
    Python over async-capable handles (the reference's DAGDriver role).

Every upstream deployment is deployed alongside the driver; the driver is
what the router/proxy expose.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from .deployment import Deployment, deployment


class _HandleProxy:
    """What the composition fn sees: call a deployment like a function."""

    def __init__(self, name: str):
        self._name = name
        self._handle = None

    def _resolve(self):
        if self._handle is None:
            from . import api as serve_api
            if "router" in serve_api._state:     # driver process
                self._handle = serve_api.get_handle(self._name)
            else:                                # inside a replica
                from .. import api as core_api
                from .handle import ServeHandle
                from .router import Router
                ctrl = core_api.get_actor("serve::controller")
                self._handle = ServeHandle(Router(ctrl), self._name)
        return self._handle

    def __call__(self, *args, **kwargs):
        """Synchronous call-through (stages run remotely; the driver
        deployment blocks on the result)."""
        return self._resolve().remote(*args, **kwargs).result(
            timeout_s=300.0)

    def remote(self, *args, **kwargs):
        """Async: returns the tracked ref (compose fan-out/ensembles)."""
        return self._resolve().remote(*args, **kwargs)


def composed(fn: Callable, *, deployments: Dict[str, Deployment],
             name: Optional[str] = None,
             **driver_options) -> Deployment:
    """A driver deployment running ``fn(handles, *args, **kwargs)`` with a
    `_HandleProxy` per upstream deployment."""
    dep_names = {key: d.name for key, d in deployments.items()}

    class _Driver:
        def __init__(self):
            self._handles = {key: _HandleProxy(dname)
                             for key, dname in dep_names.items()}

        def __call__(self, *args, **kwargs):
            return fn(self._handles, *args, **kwargs)

    _Driver.__name__ = name or getattr(fn, "__name__", "graph_driver")
    driver = deployment(_Driver, name=name or f"{_Driver.__name__}",
                        **driver_options)
    driver._upstreams = list(deployments.values())  # deployed by run_graph
    return driver


def pipeline(stages: List[Deployment], *, name: str = "pipeline",
             **driver_options) -> Deployment:
    """Linear chain: output of stage i feeds stage i+1."""
    keys = [f"s{i}" for i in range(len(stages))]

    def chain(handles, *args, **kwargs):
        out = handles[keys[0]](*args, **kwargs)
        for k in keys[1:]:
            out = handles[k](out)
        return out

    return composed(chain, deployments=dict(zip(keys, stages)), name=name,
                    **driver_options)


def run_graph(driver: Deployment, *, route_prefix: Optional[str] = None):
    """Deploy every upstream deployment, then the driver (the routable
    endpoint).  Returns the driver's handle."""
    from . import api as serve_api
    for up in getattr(driver, "_upstreams", []):
        serve_api.run(up, route_prefix=None)  # handle-only: no HTTP route
    return serve_api.run(driver, route_prefix=route_prefix or "__derive__")
