"""@serve.batch: transparent request batching inside a replica.

Capability mirror of the reference's `serve/batching.py` — callers invoke
the wrapped function with single items; the wrapper groups up to
``max_batch_size`` concurrent calls (waiting ``batch_wait_timeout_s``) and
invokes the underlying function ONCE with the list.  Thread-based (replicas
run with max_concurrency > 1): the first arrival becomes the flush leader.
On TPU replicas this is what keeps the MXU fed — batched pjit calls instead
of batch-1 inference.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Any, Callable, List, Optional


class _Slot:
    __slots__ = ("args", "event", "result", "error")

    def __init__(self, args):
        self.args = args
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None


class _Batcher:
    def __init__(self, fn: Callable[[List[Any]], List[Any]],
                 max_batch_size: int, timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout_s = timeout_s
        self._lock = threading.Lock()
        self._full = threading.Condition(self._lock)
        self._queue: List[_Slot] = []
        self._leader = False

    def submit(self, item: Any) -> Any:
        slot = _Slot(item)
        lead = False
        with self._lock:
            self._queue.append(slot)
            if not self._leader:
                self._leader = lead = True
            elif len(self._queue) >= self.max_batch_size:
                # the arrival that fills the batch wakes the waiting
                # leader NOW — the old 1 ms sleep-poll added up to a
                # full poll interval of dead time per flush, a visible
                # p50 tax at small batch_wait_timeout_s
                self._full.notify()
        if lead:
            self._flush_as_leader()
        slot.event.wait()
        if slot.error is not None:
            raise slot.error
        return slot.result

    def _flush_as_leader(self) -> None:
        deadline = time.monotonic() + self.timeout_s
        with self._full:
            while len(self._queue) < self.max_batch_size:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._full.wait(remaining):
                    break
        with self._lock:
            batch = self._queue[:self.max_batch_size]
            self._queue = self._queue[self.max_batch_size:]
            self._leader = bool(self._queue)
            requeue_leader = self._leader
        try:
            from ..util import tracing
            with tracing.span(
                    f"serve_batch::{getattr(self.fn, '__name__', 'batch')}",
                    "serve", batch_size=len(batch)):
                results = self.fn([s.args for s in batch])
            if results is None or len(results) != len(batch):
                raise ValueError(
                    "@serve.batch function must return one result per "
                    f"input ({len(batch)} in, "
                    f"{0 if results is None else len(results)} out)")
            for s, r in zip(batch, results):
                s.result = r
        except BaseException as e:
            for s in batch:
                s.error = e
        finally:
            for s in batch:
                s.event.set()
            if requeue_leader:
                self._flush_as_leader()


def batch(_fn: Optional[Callable] = None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorate ``fn(self, items: list) -> list`` (or a free function taking
    a list); call sites pass single items."""

    def wrap(fn: Callable):
        batchers = {}  # per bound instance

        @functools.wraps(fn)
        def wrapper(*args):
            if len(args) == 2:  # bound method: (self, item)
                owner, item = args
                key = id(owner)
                call = lambda items: fn(owner, items)  # noqa: E731
            else:
                (item,) = args
                key = 0
                call = fn
            b = batchers.get(key)
            if b is None:
                b = batchers.setdefault(
                    key, _Batcher(call, max_batch_size,
                                  batch_wait_timeout_s))
            return b.submit(item)

        wrapper._is_serve_batch = True
        return wrapper

    return wrap(_fn) if _fn is not None else wrap
