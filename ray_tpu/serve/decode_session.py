"""Stateful KV-cache decode sessions for Serve replicas.

The serving-side face of the model runtime (reference: Ray Serve
delegates streaming decode to external engines like vLLM —
/root/reference/doc/source/serve/index.md; here it is in-tree): a
replica holds per-session KV caches so `start` pays one prefill and
every `next` is a single decode step.  Used by the streaming-decode
example and `bench.py --serve`; wrap it in a `@serve.deployment` whose
``__call__`` forwards to :meth:`handle`.

Two decode data planes live here:

* **Continuous-batching engine** (default): a fixed-slot batched KV
  cache (`models.init_slot_cache`) and ONE jitted batched decode step
  shared by every live session.  A background loop decodes all active
  slots each iteration; sessions join and vacate BETWEEN steps
  (iteration-level admission — vLLM's scheduling insight), never
  recompiling: the batch shape is pinned at ``max_slots`` and the slot
  index of admission is a traced argument.  Decoded tokens land in
  per-session bounded queues that the proxy drains via ``next_chunk``
  (N tokens per RPC round trip) — this is what closes the measured 4×
  serve-vs-raw decode gap: batch-1 decode steps and one RPC per token
  both disappear.

* **Legacy per-call path** (``engine=False`` or batched prompts): the
  original pop-as-lease session table, one eager `next` per token.
  Kept as the fallback for non-session deployments and B>1 prompt
  batches.

prefill/decode compile ONCE per replica (config static, cache position
dynamic) — eager per-step dispatch costs ~100x on small models, which
the round-4 TTFT benchmark measured directly (700 ms → 4.8 ms/token).
"""

from __future__ import annotations

import atexit
import collections
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

from .config import DecodeEngineConfig

#: live engines, drained at interpreter exit — a daemon thread still
#: dispatching jitted steps while CPython tears down segfaults the
#: process (observed on this image), so every loop must be stopped and
#: joined BEFORE the runtime goes away
_ENGINES: "weakref.WeakSet" = weakref.WeakSet()


@atexit.register
def _shutdown_engines() -> None:
    for eng in list(_ENGINES):
        try:
            eng.shutdown()
            t = eng._thread
            if t is not None and t.is_alive():
                t.join(timeout=5.0)
        except Exception:
            pass


class _EngineSession:
    """One live session inside the engine: its slot (or None while
    waiting for admission), bounded token queue, and terminal state."""

    __slots__ = ("sid", "slot", "queue", "last_tok", "pos", "done",
                 "error", "ended", "seq", "last_poll")

    def __init__(self, sid: str, last_tok: int, pos: int,
                 seq_base: int = 0):
        self.sid = sid
        self.slot: Optional[int] = None
        self.queue: collections.deque = collections.deque()
        self.last_tok = last_tok      # feeds the next decode step
        self.pos = pos                # host mirror of cache pos
        self.done = False             # no more tokens will be produced
        self.error: Optional[str] = None
        self.ended = False            # client sent `end`
        # seq of the next token to be DELIVERED (the start/resume reply
        # itself carries token seq_base) — replies stamp their first
        # token's seq so the failover client can dedupe replayed tokens
        # and detect a destructively-popped chunk whose reply was lost
        self.seq = seq_base + 1
        self.last_poll = time.monotonic()  # leak-reaper clock


class ContinuousBatchingEngine:
    """Replica-resident continuous-batching decode loop.

    All slot-cache mutation happens on the engine thread, between
    steps — callers only enqueue admissions and drain token queues
    under the engine condition variable, so no device array is ever
    raced."""

    def __init__(self, cfg, max_len: int, params: Any, prefill_fn,
                 engine_cfg: DecodeEngineConfig, name: str = "",
                 replica_tag: str = "local"):
        import jax
        import jax.numpy as jnp

        from ..models import cache_insert_slot, decode_step_slots
        self.cfg = cfg
        self.max_len = max_len
        self.params = params
        self.ecfg = engine_cfg
        self.name = name or "decode"
        self._tag = replica_tag
        self._prefill = prefill_fn

        def fused_step(params, tok, cache, active, *, cfg):
            # decode + greedy sample + carry in ONE program: the loop
            # pays a single dispatch and a single [S]-int32 device→host
            # read per step (separate argmax/where calls measurably
            # dominated the step on small models)
            logits, cache = decode_step_slots(params, tok, cache,
                                              active, cfg)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return jnp.where(active, nxt, tok), cache

        self._step = jax.jit(fused_step, static_argnames=("cfg",))
        self._insert = jax.jit(cache_insert_slot)
        self._cache = None            # allocated lazily on first start
        self._cond = threading.Condition()
        self.sessions: Dict[str, _EngineSession] = {}  # insertion = LRU
        self._pending: List[Tuple[_EngineSession, Any]] = []
        self._free: List[int] = list(range(engine_cfg.max_slots))
        self._slots: Dict[int, _EngineSession] = {}
        self._next_sid = 0
        self._thread: Optional[threading.Thread] = None
        self._shutdown = False
        self._draining = False   # replica evacuating: hand sessions off
        self.steps = 0
        self.tokens = 0
        self.reaped = 0          # sessions evicted by the idle reaper

    # ------------------------------------------------------------ client ops

    def start(self, prompt, max_sessions: int, seq_base: int = 0,
              teacher_forced: bool = False) -> Dict[str, Any]:
        """Prefill one batch-1 prompt and enqueue the session for
        iteration-level admission; returns immediately with the sid and
        first token (a waiting session's tokens start flowing once a
        slot frees).

        ``teacher_forced`` is the failover-resume path: ``prompt`` is a
        full replay prefix (original prompt + every token already
        delivered to the client) walked through the bounded-compile
        :func:`models.resume_prefill` programs, and the session's token
        seqs continue from ``seq_base`` so the client can splice the
        resumed stream in without duplicates or gaps."""
        import jax.numpy as jnp

        from ..exceptions import ReplicaUnavailableError
        from ..models import init_kv_cache
        with self._cond:
            if self._draining:
                raise ReplicaUnavailableError(self.name)
            if not self._free and len(self._pending) >= self.ecfg.max_waiting:
                raise ReplicaUnavailableError(self.name)
        cache = init_kv_cache(self.cfg, 1, self.max_len)
        if teacher_forced:
            from ..models import resume_prefill
            logits, cache = resume_prefill(self.params, prompt, self.cfg,
                                           cache)
        else:
            logits, cache = self._prefill(self.params, prompt,
                                          cfg=self.cfg, cache=cache)
        tok = int(jnp.argmax(logits, axis=-1).astype(jnp.int32)[0])
        with self._cond:
            # admission re-check: concurrent starts raced the prefill
            # (a drain may also have begun while we were prefilling)
            if self._draining:
                raise ReplicaUnavailableError(self.name)
            if not self._free and len(self._pending) >= self.ecfg.max_waiting:
                raise ReplicaUnavailableError(self.name)
            sid = f"{self._tag}:{self._next_sid}"
            self._next_sid += 1
            sess = _EngineSession(sid, tok, int(prompt.shape[1]),
                                  seq_base=seq_base)
            if sess.pos >= self.max_len:
                sess.done = True      # prompt filled the cache exactly
            # LRU bound on ABANDONED sessions: evict the oldest
            # slot-less finished session (ended clients pop themselves)
            while len(self.sessions) >= max_sessions:
                victim = next((s for s in self.sessions.values()
                               if s.slot is None and s.done), None)
                if victim is None:
                    break
                self.sessions.pop(victim.sid)
            self.sessions[sid] = sess
            if not sess.done:
                self._pending.append((sess, cache))
            self._ensure_thread()
            self._cond.notify_all()
        reply = {"sid": sid, "token": [tok], "proto": "chunk",
                 "seq": seq_base}
        if sess.done:
            reply["done"] = True   # prompt/replay prefix filled the cache
        return reply

    def next_chunk(self, sid: str, max_tokens: int = 16,
                   timeout_s: Optional[float] = None) -> Dict[str, Any]:
        """Drain up to ``max_tokens`` buffered tokens (blocking until at
        least one is available, the session finishes, or the timeout).
        Once one token is buffered, lingers ``chunk_linger_s`` for the
        chunk to fill so one RPC round trip carries many tokens."""
        max_tokens = max(1, int(max_tokens))
        deadline = time.monotonic() + (timeout_s if timeout_s is not None
                                       else self.ecfg.chunk_timeout_s)
        linger_deadline = None
        with self._cond:
            sess = self.sessions.get(sid)
            if sess is None:
                return {"error": f"unknown session {sid!r} (ended, "
                                 f"evicted, or never started)"}
            sess.last_poll = time.monotonic()
            while True:
                if sess.error is not None:
                    return {"error": sess.error, "done": True}
                if self._draining:
                    break   # hand off what's buffered, don't wait
                if len(sess.queue) >= max_tokens or \
                        (sess.queue and sess.done):
                    break
                now = time.monotonic()
                if sess.queue:
                    if linger_deadline is None:
                        linger_deadline = now + self.ecfg.chunk_linger_s
                    if now >= linger_deadline:
                        break
                    wait = min(linger_deadline, deadline) - now
                else:
                    if sess.done:
                        return {"tokens": [], "done": True,
                                "seq": sess.seq}
                    wait = deadline - now
                if wait <= 0:
                    break
                self._cond.wait(wait)
            first_seq = sess.seq
            toks = [sess.queue.popleft()
                    for _ in range(min(len(sess.queue), max_tokens))]
            sess.seq += len(toks)
            done = sess.done and not sess.queue
            out = {"tokens": toks, "done": done, "seq": first_seq}
            if self._draining and not done:
                # replica evacuating: deliver the buffered tokens and
                # hand the session over — the failover client re-admits
                # it (teacher-forced resume) on a healthy replica, and
                # popping it here lets the controller's migration wait
                # see the live-session count drain to zero
                out["migrating"] = True
                sess.done = True
                sess.ended = True
                self.sessions.pop(sid, None)
            # draining may un-pause a slot whose queue was full
            self._cond.notify_all()
        return out

    def end(self, sid: str) -> bool:
        with self._cond:
            sess = self.sessions.pop(sid, None)
            if sess is None:
                return False
            sess.ended = True
            sess.done = True
            self._cond.notify_all()   # engine loop vacates the slot
        return True

    def stats(self) -> Dict[str, Any]:
        with self._cond:
            return {"max_slots": self.ecfg.max_slots,
                    "occupied_slots": len(self._slots),
                    "waiting": len(self._pending),
                    "sessions": len(self.sessions),
                    "live_sessions": self._live_locked(),
                    "draining": self._draining,
                    "reaped": self.reaped,
                    "steps": self.steps, "tokens": self.tokens}

    def _live_locked(self) -> int:
        """Sessions a client may still come back for (not `end`ed):
        the controller's drain wait counts these toward zero before
        stopping the replica."""
        return sum(1 for s in self.sessions.values() if not s.ended)

    def begin_drain(self) -> int:
        """Enter drain mode: shed new starts/resumes with the typed
        ReplicaUnavailableError, stop stepping, and hand every live
        session off on its next `next_chunk` poll (buffered tokens are
        still delivered, stamped with a ``migrating`` flag that sends
        the failover client to a healthy replica).  Returns the number
        of sessions awaiting handoff."""
        with self._cond:
            self._draining = True
            n = self._live_locked()
            self._cond.notify_all()   # wake blocked next_chunk waits
        return n

    def live_sessions(self) -> int:
        with self._cond:
            return self._live_locked()

    def shutdown(self) -> None:
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()

    # ------------------------------------------------------------ engine loop

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            _ENGINES.add(self)
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"decode-engine:{self.name}")
            self._thread.start()

    def _reap_locked(self) -> None:
        """Vacate slots of ended/finished sessions (between steps), and
        evict sessions whose client stopped polling: an abandoned stream
        (client crashed, never sent `end`) would otherwise decode to its
        queue bound and then hold a slot plus session-table memory
        forever."""
        ttl = getattr(self.ecfg, "session_idle_ttl_s", 0.0) or 0.0
        if ttl > 0:
            now = time.monotonic()
            for sid, sess in list(self.sessions.items()):
                if not sess.ended and now - sess.last_poll > ttl:
                    sess.done = True      # slot vacated just below
                    sess.ended = True
                    self.sessions.pop(sid, None)
                    self.reaped += 1
        for slot, sess in list(self._slots.items()):
            if sess.done:
                del self._slots[slot]
                sess.slot = None
                self._free.append(slot)

    def _admit_locked(self) -> List[Tuple[_EngineSession, Any, int]]:
        admitted = []
        if self._draining:
            return admitted   # evacuating: no new slot occupancy
        while self._free and self._pending:
            sess, cache = self._pending.pop(0)
            if sess.ended:
                continue              # ended while waiting
            slot = self._free.pop()
            sess.slot = slot
            self._slots[slot] = sess
            admitted.append((sess, cache, slot))
        return admitted

    def _collect_locked(self) -> List[_EngineSession]:
        """Slots decoding THIS step: live sessions with queue room.
        A draining engine stops stepping — every live session is being
        handed to a healthy replica, and the replay there regenerates
        anything this engine would have decoded."""
        if self._draining:
            return []
        return [s for s in self._slots.values()
                if not s.done and
                len(s.queue) < self.ecfg.token_queue_depth]

    def _loop(self) -> None:
        import numpy as np

        import jax.numpy as jnp

        from ..core.runtime_metrics import (SERVE_DECODE_OCCUPANCY,
                                            SERVE_TOKENS)
        from ..models import init_slot_cache
        from ..util import tracing
        if self._cache is None:
            self._cache = init_slot_cache(self.cfg, self.ecfg.max_slots,
                                          self.max_len)
        tokens = np.zeros(self.ecfg.max_slots, np.int32)
        tok_dev = None       # device-resident step output → next input
        active_dev = None
        active_key: Any = None
        while True:
            with self._cond:
                while not self._shutdown:
                    self._reap_locked()
                    admitted = self._admit_locked()
                    batch = self._collect_locked()
                    if admitted or batch:
                        break
                    self._cond.wait(0.5)
                if self._shutdown:
                    return
                active = np.zeros(self.ecfg.max_slots, bool)
                for s in batch:
                    active[s.slot] = True
                    tokens[s.slot] = s.last_tok
            # ---- device work, OUTSIDE the lock (nobody else touches
            # the slot cache, and client ops must not stall on compute)
            t0 = time.time()
            try:
                for _sess, cache, slot in admitted:
                    self._cache = self._insert(self._cache, cache,
                                               jnp.int32(slot))
                if not batch:
                    continue          # admissions only: step next round
                if admitted or tok_dev is None or \
                        active_key != tuple(active):
                    # membership changed: re-upload the [S] token/mask
                    # rows; on a steady batch the step output feeds the
                    # next step directly from device memory
                    tok_dev = jnp.asarray(tokens)
                    active_dev = jnp.asarray(active)
                    active_key = tuple(active)
                tok_dev, self._cache = self._step(
                    self.params, tok_dev, self._cache, active_dev,
                    cfg=self.cfg)
                new_toks = np.asarray(tok_dev)
                tokens[:] = new_toks
            except Exception as e:                 # pragma: no cover
                with self._cond:
                    for s in batch:
                        s.error = f"decode engine step failed: {e!r}"
                        s.done = True
                    self._cond.notify_all()
                tok_dev = None
                continue
            occupancy = len(batch)
            tracing.record_span(f"serve_decode_step::{self.name}",
                                "serve", t0, time.time(),
                                batch=occupancy, deployment=self.name)
            SERVE_DECODE_OCCUPANCY.observe(occupancy,
                                           {"deployment": self.name})
            SERVE_TOKENS.inc(occupancy, {"deployment": self.name})
            with self._cond:
                self.steps += 1
                self.tokens += occupancy
                for s in batch:
                    tok = int(new_toks[s.slot])
                    s.last_tok = tok
                    s.pos += 1
                    if not s.ended:
                        s.queue.append(tok)
                    if s.pos >= self.max_len:
                        s.done = True  # cache full: slot reaped next turn
                self._cond.notify_all()


class DecodeSessionCore:
    """Session store + compiled prefill/decode over one model.

    Protocol (msgpack/JSON-native):
      {"op": "start", "prompt": [S ints] | [[S ints]xB]} ->
          {"sid": str|int, "token": [B ints]} (+ {"proto": "chunk",
          "seq": 0} when the continuous-batching engine owns the
          session)
      {"op": "resume", "prompt": [S ints], "generated": [G ints]} ->
          same shape as an engine start, with "seq": G — failover
          re-admission: teacher-forced prefix prefill of
          prompt+generated into a fresh engine slot; the returned token
          is exactly the one the uninterrupted session would have
          produced next (greedy decode is deterministic)
      {"op": "next", "sid": ...} -> {"token": [B ints]}
      {"op": "next_chunk", "sid": str, "max_tokens": N} ->
          {"tokens": [<=N ints], "done": bool, "seq": first token's
          seq} (+ {"migrating": true} when the replica is draining and
          the session must be resumed elsewhere)
      {"op": "end", "sid": ...} -> {"ended": bool}
      {"op": "stats"} -> engine/session counters (tests, dashboards)

    Engine sessions (single-prompt starts, the serving hot path) carry
    STRING sids of the form ``<replica_tag>:<n>`` — the prefix is the
    owning replica, which the proxy/router use for sid-sticky routing.
    Batched (B>1) prompts and ``engine=False`` cores use the legacy
    integer-sid path: pop-as-lease (a pipelined second `next` on the
    SAME sid — or a stale/unknown sid — gets an ``{"error": ...}``
    reply instead of racing the first), LRU-bounded ``max_sessions``.
    """

    def __init__(self, cfg, max_len: int, seed: int = 0,
                 params: Any = None, max_sessions: int = 64,
                 prefill_chunk: int = 0,
                 engine: Any = True):
        """``prefill_chunk > 0`` prefills in fixed-size chunks through
        one small reusable program instead of a whole-prompt compile —
        for models whose full-prompt flash prefill is a compile-helper
        killer (llama-family GQA, SURVEY §9).  ``engine`` is True
        (default), False, or a :class:`DecodeEngineConfig`."""
        import jax

        from ..models import decode_step, init_params, prefill
        from ..models import prefill_chunked
        self.cfg = cfg
        self.max_len = max_len
        self.max_sessions = max_sessions
        if params is None:
            params, _ = init_params(jax.random.PRNGKey(seed), cfg)
        self.params = params
        if prefill_chunk > 0:
            def chunked(params, prompt, *, cfg, cache):
                return prefill_chunked(params, prompt, cfg, cache,
                                       chunk=prefill_chunk)

            self._prefill = chunked
        else:
            self._prefill = jax.jit(prefill, static_argnames=("cfg",))
        self._decode = jax.jit(decode_step, static_argnames=("cfg",))
        self._lock = threading.Lock()
        self.sessions: Dict[int, Any] = {}   # insertion-ordered = LRU
        self._next_sid = 0
        if engine is False or engine is None:
            self._engine_cfg = None
        elif isinstance(engine, DecodeEngineConfig):
            self._engine_cfg = engine
        else:
            self._engine_cfg = DecodeEngineConfig()
        self._engine: Optional[ContinuousBatchingEngine] = None

    @property
    def engine(self) -> Optional[ContinuousBatchingEngine]:
        """The continuous-batching engine, created on first use (slot
        cache memory is only paid by cores that actually serve).
        Creation is locked: two concurrent `start` ops racing the lazy
        init would strand one session in an engine nothing references
        — and hand out colliding ``<tag>:0`` sids."""
        if self._engine is None and self._engine_cfg is not None:
            with self._lock:
                if self._engine is None:
                    name, tag = "decode", "local"
                    try:
                        from .replica import get_replica_context
                        ctx = get_replica_context()
                        name, tag = ctx.deployment, ctx.replica_tag
                    except RuntimeError:
                        pass
                    self._engine = ContinuousBatchingEngine(
                        self.cfg, self.max_len, self.params,
                        self._prefill, self._engine_cfg,
                        name=name, replica_tag=tag)
        return self._engine

    def handle(self, req: Dict[str, Any]) -> Dict[str, Any]:
        import jax.numpy as jnp

        from ..models import init_kv_cache
        op = req["op"]
        if op == "start":
            prompt = jnp.asarray(req["prompt"], jnp.int32)
            if prompt.ndim == 1:
                prompt = prompt[None]
            if self._engine_cfg is not None and prompt.shape[0] == 1:
                return self.engine.start(prompt, self.max_sessions)
            cache = init_kv_cache(self.cfg, prompt.shape[0],
                                  self.max_len)
            logits, cache = self._prefill(self.params, prompt,
                                          cfg=self.cfg, cache=cache)
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            with self._lock:
                sid = self._next_sid
                self._next_sid += 1
                self.sessions[sid] = (cache, tok)
                while len(self.sessions) > self.max_sessions:
                    self.sessions.pop(next(iter(self.sessions)))
            return {"sid": sid, "token": tok.tolist()}
        if op == "resume":
            # failover re-admission (serve/failover.py): replay the
            # journal — prompt + every token the client already has —
            # through a teacher-forced prefix prefill into a fresh
            # engine slot, continuing seqs at len(generated)
            if self._engine_cfg is None:
                return {"error": "resume requires the continuous-"
                                 "batching engine (engine=False core)"}
            prompt = req["prompt"]
            if prompt and isinstance(prompt[0], (list, tuple)):
                prompt = prompt[0]     # batched form: engine is B=1
            generated = list(req.get("generated") or [])
            prefix = jnp.asarray([list(prompt) + generated], jnp.int32)
            return self.engine.start(prefix, self.max_sessions,
                                     seq_base=len(generated),
                                     teacher_forced=True)
        if op == "stats":
            out = {"legacy_sessions": len(self.sessions)}
            if self._engine is not None:
                out["engine"] = self._engine.stats()
            return out
        sid = req.get("sid")
        if op == "end":
            if isinstance(sid, str):
                if self._engine is None:
                    return {"ended": False}
                return {"ended": self._engine.end(sid)}
            with self._lock:
                return {"ended":
                        self.sessions.pop(sid, None) is not None}
        if op == "next_chunk":
            if not isinstance(sid, str) or self._engine is None:
                # legacy sessions have no token queue: one step per call
                out = self._legacy_next(sid)
                if "error" in out:
                    return out
                return {"tokens": out["token"], "done": False}
            return self._engine.next_chunk(
                sid, req.get("max_tokens", 16), req.get("timeout_s"))
        # op == "next"
        if isinstance(sid, str) and self._engine is not None:
            out = self._engine.next_chunk(sid, 1)
            if "error" in out:
                return out
            if not out["tokens"]:
                return {"error": f"session {sid!r} finished "
                                 f"(cache capacity reached)"}
            reply = {"token": out["tokens"]}
            if out["done"]:
                reply["eos"] = True
            return reply
        return self._legacy_next(sid)

    def _legacy_next(self, sid) -> Dict[str, Any]:
        import jax.numpy as jnp
        with self._lock:
            entry = self.sessions.pop(sid, None)
        if entry is None:
            return {"error": f"unknown session {sid!r} (ended, "
                             f"evicted, or decoding in another request)"}
        cache, tok = entry
        logits, cache = self._decode(self.params, tok, cache,
                                     cfg=self.cfg)
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        with self._lock:
            self.sessions[sid] = (cache, tok)
        return {"token": tok.tolist()}
