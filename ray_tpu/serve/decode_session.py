"""Stateful KV-cache decode sessions for Serve replicas.

The serving-side face of the model runtime (reference: Ray Serve
delegates streaming decode to external engines like vLLM —
/root/reference/doc/source/serve/index.md; here it is in-tree): a
replica holds per-session KV caches so `start` pays one prefill and
every `next` is a single decode step.  Used by the streaming-decode
example and `bench.py --serve`; wrap it in a `@serve.deployment` whose
``__call__`` forwards to :meth:`handle`.

prefill/decode compile ONCE per replica (config static, cache position
dynamic) — eager per-step dispatch costs ~100x on small models, which
the round-4 TTFT benchmark measured directly (700 ms → 4.8 ms/token).
"""

from __future__ import annotations

import threading
from typing import Any, Dict


class DecodeSessionCore:
    """Session store + compiled prefill/decode over one model.

    Protocol (msgpack/JSON-native):
      {"op": "start", "prompt": [S ints] | [[S ints]xB]} ->
          {"sid": int, "token": [B ints]}
      {"op": "next", "sid": int} -> {"token": [B ints]}
      {"op": "end", "sid": int} -> {"ended": bool}
    Sessions are popped while decoding (pop-as-lease): a pipelined
    second `next` on the SAME sid — or a stale/unknown sid — gets an
    ``{"error": ...}`` reply instead of racing the first.  KV caches
    are real memory, so the table is LRU-bounded (``max_sessions``) and
    clients should send ``end``; an evicted session's next call errors.
    """

    def __init__(self, cfg, max_len: int, seed: int = 0,
                 params: Any = None, max_sessions: int = 64,
                 prefill_chunk: int = 0):
        """``prefill_chunk > 0`` prefills in fixed-size chunks through
        one small reusable program instead of a whole-prompt compile —
        for models whose full-prompt flash prefill is a compile-helper
        killer (llama-family GQA, SURVEY §9)."""
        import jax

        from ..models import decode_step, init_params, prefill
        from ..models import prefill_chunked
        self.cfg = cfg
        self.max_len = max_len
        self.max_sessions = max_sessions
        if params is None:
            params, _ = init_params(jax.random.PRNGKey(seed), cfg)
        self.params = params
        if prefill_chunk > 0:
            def chunked(params, prompt, *, cfg, cache):
                return prefill_chunked(params, prompt, cfg, cache,
                                       chunk=prefill_chunk)

            self._prefill = chunked
        else:
            self._prefill = jax.jit(prefill, static_argnames=("cfg",))
        self._decode = jax.jit(decode_step, static_argnames=("cfg",))
        self._lock = threading.Lock()
        self.sessions: Dict[int, Any] = {}   # insertion-ordered = LRU
        self._next_sid = 0

    def handle(self, req: Dict[str, Any]) -> Dict[str, Any]:
        import jax.numpy as jnp

        from ..models import init_kv_cache
        if req["op"] == "start":
            prompt = jnp.asarray(req["prompt"], jnp.int32)
            if prompt.ndim == 1:
                prompt = prompt[None]
            cache = init_kv_cache(self.cfg, prompt.shape[0],
                                  self.max_len)
            logits, cache = self._prefill(self.params, prompt,
                                          cfg=self.cfg, cache=cache)
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            with self._lock:
                sid = self._next_sid
                self._next_sid += 1
                self.sessions[sid] = (cache, tok)
                while len(self.sessions) > self.max_sessions:
                    self.sessions.pop(next(iter(self.sessions)))
            return {"sid": sid, "token": tok.tolist()}
        if req["op"] == "end":
            with self._lock:
                return {"ended":
                        self.sessions.pop(req["sid"], None) is not None}
        with self._lock:
            entry = self.sessions.pop(req["sid"], None)
        if entry is None:
            return {"error": f"unknown session {req['sid']!r} (ended, "
                             f"evicted, or decoding in another request)"}
        cache, tok = entry
        logits, cache = self._decode(self.params, tok, cache,
                                     cfg=self.cfg)
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        with self._lock:
            self.sessions[req["sid"]] = (cache, tok)
        return {"token": tok.tolist()}
