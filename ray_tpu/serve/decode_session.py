"""Stateful KV-cache decode sessions for Serve replicas.

The serving-side face of the model runtime (reference: Ray Serve
delegates streaming decode to external engines like vLLM —
/root/reference/doc/source/serve/index.md; here it is in-tree): a
replica holds per-session KV caches so `start` pays one prefill and
every `next` is a single decode step.  Used by the streaming-decode
example and `bench.py --serve`; wrap it in a `@serve.deployment` whose
``__call__`` forwards to :meth:`handle`.

Two decode data planes live here:

* **Continuous-batching engine** (default): a fixed-slot batched KV
  cache (`models.init_slot_cache`) and ONE jitted batched decode step
  shared by every live session.  A background loop decodes all active
  slots each iteration; sessions join and vacate BETWEEN steps
  (iteration-level admission — vLLM's scheduling insight), never
  recompiling: the batch shape is pinned at ``max_slots`` and the slot
  index of admission is a traced argument.  Decoded tokens land in
  per-session bounded queues that the proxy drains via ``next_chunk``
  (N tokens per RPC round trip) — this is what closes the measured 4×
  serve-vs-raw decode gap: batch-1 decode steps and one RPC per token
  both disappear.

  Two model-side optimisations compound inside the loop:

  - **Chunked-prefill admission**: a joining session's prompt is
    consumed ``[1, chunk]`` tokens at a time between shared decode
    steps (``DecodeEngineConfig.prefill_chunk_tokens``), so a join
    stalls live streams by at most one chunk interval instead of a
    whole prompt forward, and TTFT-under-load stops being
    O(prompt_len) of batch stall.  Admission, failover resume
    (``op: resume``), and the legacy ``prefill_chunked`` path all
    dispatch the SAME module-level chunk programs
    (`models.prefill_chunk_jit`) — at most two compiled prefill shapes
    per model, whatever the traffic.
  - **Speculative decoding** (``DecodeEngineConfig.spec_draft`` /
    ``spec_k``): a draft model proposes k tokens per iteration in one
    scanned dispatch (`models.draft_propose_slots`) and the target
    verifies all of them plus a bonus token in one k+1-wide batched
    forward (`models.verify_step_slots`) — 2 dispatches for 1..k+1
    tokens per slot.  Greedy acceptance is exact-match, so streams
    (and the PR-5 seq-based replay journal) stay byte-identical to
    plain decode; any draft/verify fault falls back to a plain step
    (chaos site ``serve.spec_verify``), never corrupting a stream.

* **Eager per-call path** (``engine=False`` ONLY): the original
  pop-as-lease session table, one eager `next` per token.  Kept solely
  for non-LM deployments and as the parity oracle in tests — an
  engine-enabled core routes EVERYTHING through the engine (B>1 prompt
  batches become per-row engine sessions behind a group sid), so a
  replica has exactly one decode data plane to route, autoscale, and
  journal, and never compiles the whole-prompt prefill program at all.

prefill/decode compile ONCE per replica (config static, cache position
dynamic) — eager per-step dispatch costs ~100x on small models, which
the round-4 TTFT benchmark measured directly (700 ms → 4.8 ms/token).
"""

from __future__ import annotations

import atexit
import collections
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

from .config import DecodeEngineConfig

#: live engines, drained at interpreter exit — a daemon thread still
#: dispatching jitted steps while CPython tears down segfaults the
#: process (observed on this image), so every loop must be stopped and
#: joined BEFORE the runtime goes away
_ENGINES: "weakref.WeakSet" = weakref.WeakSet()


@atexit.register
def _shutdown_engines() -> None:
    for eng in list(_ENGINES):
        try:
            eng.shutdown()
            t = eng._thread
            if t is not None and t.is_alive():
                t.join(timeout=5.0)
        except Exception:
            pass


class _EngineSession:
    """One live session inside the engine, through three phases:
    *prefilling* (the engine thread consumes its prompt one fixed-shape
    chunk program at a time, between decode steps), *waiting* (prompt
    fully prefilled into a batch-1 cache, first token produced, queued
    for a free slot), and *decoding* (cache inserted into its slot of
    the shared batched cache)."""

    __slots__ = ("sid", "slot", "queue", "last_tok", "pos", "done",
                 "error", "ended", "seq", "last_poll",
                 "prompt", "poff", "pcache", "dcache", "plogits",
                 "ready", "shed", "ptoks", "rid", "t_enq", "t_pf",
                 "t_ready")

    def __init__(self, sid: str, prompt: Any, seq_base: int = 0,
                 rid: str = ""):
        self.sid = sid
        # ---- per-request phase marks (monotonic clock) ----
        self.rid = rid                # proxy-minted request id ("" = none)
        self.t_enq = time.monotonic()  # enqueued for chunked admission
        self.t_pf: Optional[float] = None     # first prefill chunk ran
        self.t_ready: Optional[float] = None  # first token produced
        # host copy of the prompt tokens: the shared-prefix index key
        # (inserted when this session takes a slot, matched by later
        # admissions)
        self.ptoks: tuple = ()
        self.slot: Optional[int] = None
        self.queue: collections.deque = collections.deque()
        self.last_tok: Optional[int] = None  # set when prefill completes
        self.pos = 0                  # host mirror of cache pos
        self.done = False             # no more tokens will be produced
        self.error: Optional[str] = None
        self.ended = False            # client sent `end`
        # seq of the next token to be DELIVERED (the start/resume reply
        # itself carries token seq_base) — replies stamp their first
        # token's seq so the failover client can dedupe replayed tokens
        # and detect a destructively-popped chunk whose reply was lost
        self.seq = seq_base + 1
        self.last_poll = time.monotonic()  # leak-reaper clock
        # ---- chunked-admission state (cleared once decoding) ----
        self.prompt = prompt          # [1, S] int32 still to prefill
        self.poff = 0                 # tokens consumed so far
        self.pcache: Any = None       # target batch-1 cache being built
        self.dcache: Any = None       # draft batch-1 cache (speculating)
        self.plogits: Any = None      # last chunk's final-position logits
        self.ready = False            # first token produced; start() may return
        self.shed = False             # drained mid-admission: typed 503


class ContinuousBatchingEngine:
    """Replica-resident continuous-batching decode loop.

    All slot-cache mutation happens on the engine thread, between
    steps — callers only enqueue admissions and drain token queues
    under the engine condition variable, so no device array is ever
    raced."""

    def __init__(self, cfg, max_len: int, params: Any,
                 engine_cfg: DecodeEngineConfig, name: str = "",
                 replica_tag: str = "local"):
        import jax
        import jax.numpy as jnp

        from ..models import (cache_insert_slot, decode_step_slots,
                              draft_propose_slots, prefill_chunk_jit,
                              verify_step_slots)
        self.cfg = cfg
        self.max_len = max_len
        self.params = params
        self.ecfg = engine_cfg
        self.name = name or "decode"
        self._tag = replica_tag

        def fused_step(params, tok, cache, active, *, cfg):
            # decode + greedy sample + carry in ONE program: the loop
            # pays a single dispatch and a single [S]-int32 device→host
            # read per step (separate argmax/where calls measurably
            # dominated the step on small models)
            logits, cache = decode_step_slots(params, tok, cache,
                                              active, cfg)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return jnp.where(active, nxt, tok), cache

        # ---- dispatch profiler (util/device_profile.py) ----
        # every jitted program below goes through a wrap-once timing
        # shim: dispatch counts, sampled device time, and the compile
        # ledger (first-seen argument shapes) per program.  Snapshots
        # ride _maybe_push_metrics to the nodelet fold.
        from ..util.device_profile import DispatchProfiler
        self._prof = DispatchProfiler()
        self._step = self._prof.wrap(
            "decode_step", jax.jit(fused_step, static_argnames=("cfg",)))
        self._insert = self._prof.wrap("cache_insert",
                                       jax.jit(cache_insert_slot))
        # ---- shared-prefix KV reuse ----
        # radix trie over live slots' prompts (serve/prefix_cache.py):
        # admission copies the longest shared prefix out of a donor
        # slot and prefills only the unshared suffix.  Engine-thread
        # only, like the slot cache itself.
        self._prefix = None
        self._gather = None
        if getattr(engine_cfg, "prefix_cache", True):
            from ..models import cache_gather_slot
            from .prefix_cache import PrefixIndex
            self._prefix = PrefixIndex()
            self._gather = self._prof.wrap("prefix_gather",
                                           jax.jit(cache_gather_slot))
        self.prefix_hits = 0          # admissions seeded from a donor
        self.prefix_tokens_reused = 0  # prefill tokens skipped
        self._last_metrics_push = 0.0
        # the chunk program is the MODULE-LEVEL shared jit: admission
        # here, failover resume (models.resume_prefill), and the legacy
        # prefill_chunked path all hit one compile cache.  The profiler
        # wrap is idempotent, so an engine restart re-wrapping the same
        # shared jit never stacks a second timer over it.
        self._chunk = self._prof.wrap("prefill_chunk", prefill_chunk_jit)
        # ---- speculative decoding ----
        self._spec = False
        self._draft_cfg = None
        self._draft_params = None
        spec = engine_cfg.spec_draft
        if spec:
            if spec in ("shared", True):
                self._draft_cfg, self._draft_params = cfg, params
            elif isinstance(spec, tuple):
                self._draft_cfg, self._draft_params = spec
            else:   # a bare TransformerConfig: fresh params (tests)
                from ..models import init_params
                self._draft_cfg = spec
                self._draft_params, _ = init_params(
                    jax.random.PRNGKey(0), spec)
            if self._draft_cfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft vocab {self._draft_cfg.vocab_size} != target "
                    f"vocab {cfg.vocab_size}: proposals must be target "
                    f"token ids")
            self._spec = True
            self._draft = self._prof.wrap(
                "draft_propose", jax.jit(draft_propose_slots,
                                         static_argnames=("cfg", "k")))
            self._verify = self._prof.wrap(
                "verify", jax.jit(verify_step_slots,
                                  static_argnames=("cfg",)))
        self._spec_k = max(2, int(engine_cfg.spec_k))
        self._spec_disabled = False
        self._spec_fail_streak = 0
        self.spec_proposed = 0   # draft tokens offered to verification
        self.spec_accepted = 0   # draft tokens the target agreed with
        self.spec_fallbacks = 0  # iterations degraded to plain decode
        self._cache = None            # allocated lazily on first start
        self._dcache = None           # draft slot cache (speculating)
        self._shapes: set = set()     # distinct compiled program shapes
        self._cond = threading.Condition()
        self.sessions: Dict[str, _EngineSession] = {}  # insertion = LRU
        self._pending: List[_EngineSession] = []   # prefilled, want slot
        self._prefilling: List[_EngineSession] = []
        self._free: List[int] = list(range(engine_cfg.max_slots))
        self._slots: Dict[int, _EngineSession] = {}
        self._next_sid = 0
        self._thread: Optional[threading.Thread] = None
        self._shutdown = False
        self._draining = False   # replica evacuating: hand sessions off
        self.steps = 0
        self.tokens = 0
        self.reaped = 0          # sessions evicted by the idle reaper
        self.prefill_chunks = 0  # chunk programs run for admissions
        # analytic FLOPs/token per program -> the profiler's MFU
        # numerators (models.engine_flops_table; pure-copy programs 0)
        from ..models import engine_flops_table
        for prog, f in engine_flops_table(
                cfg, max_len, draft_cfg=self._draft_cfg).items():
            self._prof.set_flops_per_token(prog, f)
        # engine-side phase accumulators of the serve_breakdown table
        # (queue: enqueue -> first prefill chunk; admission: first
        # token -> decode slot); prefill/decode_dispatch walls come
        # from the profiler at snapshot time
        self.phase_s = {"queue": 0.0, "admission": 0.0}

    # ------------------------------------------------------------ client ops

    def start(self, prompt, max_sessions: int, seq_base: int = 0,
              teacher_forced: bool = False,
              ptoks: Optional[tuple] = None,
              rid: str = "") -> Dict[str, Any]:
        """Enqueue one batch-1 prompt for chunked admission and block
        until the ENGINE THREAD has prefilled it — `[1, chunk]` blocks
        (tail in `[1, 1]` steps) interleaved between shared decode
        steps, so a joining session never stalls live streams by more
        than one chunk interval and admission reuses the same two
        compiled chunk shapes as failover resume.  Returns the sid and
        first token; the session's remaining tokens start flowing once
        a slot frees (iteration-level admission).

        ``teacher_forced`` marks the failover-resume path: ``prompt``
        is a full replay prefix (original prompt + every token already
        delivered) and the session's token seqs continue from
        ``seq_base`` so the client can splice the resumed stream in
        without duplicates or gaps.  Resume IS admission here — both
        walk the same chunk programs, so resumes never compile-storm."""
        import jax.numpy as jnp

        from ..exceptions import ReplicaUnavailableError
        s_len = int(prompt.shape[1])
        if s_len > self.max_len:
            raise ValueError(f"prompt length {s_len} exceeds cache "
                             f"capacity {self.max_len}")
        # ``ptoks`` is the HOST copy of the prompt (the prefix-index
        # key).  handle() passes it from the request's own list —
        # reading it back off the device array here would be an extra
        # sync on the admission path
        if ptoks is None and self._prefix is not None:
            import numpy as np
            ptoks = tuple(int(t) for t in np.asarray(prompt)[0])
        prompt = jnp.asarray(prompt, jnp.int32)
        with self._cond:
            if self._draining:
                raise ReplicaUnavailableError(self.name)
            if not self._free and \
                    len(self._pending) + len(self._prefilling) \
                    >= self.ecfg.max_waiting:
                raise ReplicaUnavailableError(self.name)
            sid = f"{self._tag}:{self._next_sid}"
            self._next_sid += 1
            sess = _EngineSession(sid, prompt, seq_base=seq_base,
                                  rid=rid)
            sess.ptoks = ptoks or ()
            # LRU bound on ABANDONED sessions: evict the oldest
            # slot-less finished session (ended clients pop themselves)
            while len(self.sessions) >= max_sessions:
                victim = next((s for s in self.sessions.values()
                               if s.slot is None and s.done), None)
                if victim is None:
                    break
                self.sessions.pop(victim.sid)
            self.sessions[sid] = sess
            self._prefilling.append(sess)
            self._ensure_thread()
            self._cond.notify_all()
            deadline = time.monotonic() + \
                max(1.0, self.ecfg.admission_timeout_s)
            while not sess.ready and sess.error is None \
                    and not sess.shed and not sess.done:
                left = deadline - time.monotonic()
                if left <= 0 or self._shutdown:
                    sess.done = True
                    sess.ended = True
                    self.sessions.pop(sid, None)
                    raise ReplicaUnavailableError(self.name)
                self._cond.wait(min(left, 1.0))
            if sess.shed:     # drain began mid-admission: typed shed,
                raise ReplicaUnavailableError(self.name)  # client resumes elsewhere
            if sess.error is not None:
                raise RuntimeError(sess.error)
            if not sess.ready:   # reaped/force-ended mid-admission
                self.sessions.pop(sid, None)
                raise ReplicaUnavailableError(self.name)
            reply = {"sid": sid, "token": [sess.last_tok],
                     "proto": "chunk", "seq": seq_base}
            if sess.done:
                reply["done"] = True  # prompt/replay prefix filled the cache
        return reply

    def next_chunk(self, sid: str, max_tokens: int = 16,
                   timeout_s: Optional[float] = None) -> Dict[str, Any]:
        """Drain up to ``max_tokens`` buffered tokens (blocking until at
        least one is available, the session finishes, or the timeout).
        Once one token is buffered, lingers ``chunk_linger_s`` for the
        chunk to fill so one RPC round trip carries many tokens."""
        max_tokens = max(1, int(max_tokens))
        deadline = time.monotonic() + (timeout_s if timeout_s is not None
                                       else self.ecfg.chunk_timeout_s)
        linger_deadline = None
        with self._cond:
            sess = self.sessions.get(sid)
            if sess is None:
                return {"error": f"unknown session {sid!r} (ended, "
                                 f"evicted, or never started)"}
            sess.last_poll = time.monotonic()
            while True:
                if sess.error is not None:
                    return {"error": sess.error, "done": True}
                if self._draining:
                    break   # hand off what's buffered, don't wait
                if len(sess.queue) >= max_tokens or \
                        (sess.queue and sess.done):
                    break
                now = time.monotonic()
                if sess.queue:
                    if linger_deadline is None:
                        linger_deadline = now + self.ecfg.chunk_linger_s
                    if now >= linger_deadline:
                        break
                    wait = min(linger_deadline, deadline) - now
                else:
                    if sess.done:
                        return {"tokens": [], "done": True,
                                "seq": sess.seq}
                    wait = deadline - now
                if wait <= 0:
                    break
                self._cond.wait(wait)
            first_seq = sess.seq
            toks = [sess.queue.popleft()
                    for _ in range(min(len(sess.queue), max_tokens))]
            sess.seq += len(toks)
            done = sess.done and not sess.queue
            out = {"tokens": toks, "done": done, "seq": first_seq}
            if self._draining and not done:
                # replica evacuating: deliver the buffered tokens and
                # hand the session over — the failover client re-admits
                # it (teacher-forced resume) on a healthy replica, and
                # popping it here lets the controller's migration wait
                # see the live-session count drain to zero
                out["migrating"] = True
                sess.done = True
                sess.ended = True
                self.sessions.pop(sid, None)
            # draining may un-pause a slot whose queue was full
            self._cond.notify_all()
        return out

    def end(self, sid: str) -> bool:
        with self._cond:
            sess = self.sessions.pop(sid, None)
            if sess is None:
                return False
            sess.ended = True
            sess.done = True
            self._cond.notify_all()   # engine loop vacates the slot
        return True

    def stats(self) -> Dict[str, Any]:
        with self._cond:
            prop, acc = self.spec_proposed, self.spec_accepted
            return {"max_slots": self.ecfg.max_slots,
                    "occupied_slots": len(self._slots),
                    "waiting": len(self._pending),
                    "prefilling": len(self._prefilling),
                    "sessions": len(self.sessions),
                    "live_sessions": self._live_locked(),
                    "draining": self._draining,
                    "reaped": self.reaped,
                    "steps": self.steps, "tokens": self.tokens,
                    "prefill_chunks": self.prefill_chunks,
                    # every distinct program shape this engine has
                    # dispatched — a compile-storm regression (one
                    # program per prompt/resume length) shows up here
                    # as a count growing with traffic instead of
                    # staying O(1)
                    "program_shapes": sorted(
                        "%s:%s" % (k[0], "x".join(str(d) for d in k[1:]))
                        for k in self._shapes),
                    "distinct_program_shapes": len(self._shapes),
                    "prefix": dict(
                        (self._prefix.stats() if self._prefix is not None
                         else {"entries": 0, "hits": 0, "misses": 0,
                               "hit_rate": None, "tokens_matched": 0}),
                        applied_hits=self.prefix_hits,
                        tokens_reused=self.prefix_tokens_reused),
                    "spec": {"enabled": self._spec,
                             "disabled": self._spec_disabled,
                             "k": self._spec_k,
                             "proposed": prop, "accepted": acc,
                             "acceptance":
                                 round(acc / prop, 4) if prop else None,
                             "fallbacks": self.spec_fallbacks},
                    # data-plane flight instruments: per-program
                    # dispatch/compile/MFU ledger + phase attribution
                    "device_profile": self._prof.snapshot(),
                    "phase_totals": self.phase_totals()}

    def phase_totals(self) -> Dict[str, float]:
        """Cumulative serve-phase seconds — the serve_breakdown
        attribution sources.  queue/admission come from per-session
        marks; prefill/decode_dispatch are the profiler's per-program
        dispatch walls (engine-thread occupancy, which is what a token
        actually waits on)."""
        wall = self._prof.wall_seconds()
        prefill = sum(wall.get(p, 0.0)
                      for p in ("prefill_chunk", "prefix_gather"))
        decode = sum(wall.get(p, 0.0)
                     for p in ("decode_step", "draft_propose", "verify",
                               "cache_insert"))
        return {"queue": round(self.phase_s["queue"], 6),
                "admission": round(self.phase_s["admission"], 6),
                "prefill": round(prefill, 6),
                "decode_dispatch": round(decode, 6)}

    def _live_locked(self) -> int:
        """Sessions a client may still come back for (not `end`ed):
        the controller's drain wait counts these toward zero before
        stopping the replica."""
        return sum(1 for s in self.sessions.values() if not s.ended)

    def begin_drain(self) -> int:
        """Enter drain mode: shed new starts/resumes with the typed
        ReplicaUnavailableError, stop stepping, and hand every live
        session off on its next `next_chunk` poll (buffered tokens are
        still delivered, stamped with a ``migrating`` flag that sends
        the failover client to a healthy replica).  Sessions still
        mid-prefill are shed the same typed way — their `start` caller
        has no sid yet, so the shed IS the handoff (the failover client
        replays the journal elsewhere).  Returns the number of sessions
        awaiting handoff."""
        with self._cond:
            self._draining = True
            for sess in self._prefilling:
                sess.shed = True
                sess.done = True
                sess.ended = True
                sess.pcache = sess.dcache = sess.plogits = None
                self.sessions.pop(sess.sid, None)
            self._prefilling.clear()
            n = self._live_locked()
            self._cond.notify_all()   # wake blocked next_chunk waits
        return n

    def live_sessions(self) -> int:
        with self._cond:
            return self._live_locked()

    def shutdown(self) -> None:
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()

    # ------------------------------------------------------------ engine loop

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            _ENGINES.add(self)
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"decode-engine:{self.name}")
            self._thread.start()

    def _reap_locked(self) -> None:
        """Vacate slots of ended/finished sessions (between steps), and
        evict sessions whose client stopped polling: an abandoned stream
        (client crashed, never sent `end`) would otherwise decode to its
        queue bound and then hold a slot plus session-table memory
        forever."""
        ttl = getattr(self.ecfg, "session_idle_ttl_s", 0.0) or 0.0
        if ttl > 0:
            now = time.monotonic()
            for sid, sess in list(self.sessions.items()):
                if not sess.ended and now - sess.last_poll > ttl:
                    sess.done = True      # slot vacated just below
                    sess.ended = True
                    self.sessions.pop(sid, None)
                    self.reaped += 1
        for slot, sess in list(self._slots.items()):
            if sess.done:
                del self._slots[slot]
                sess.slot = None
                self._free.append(slot)
                # the prefix index KEEPS a freed slot's entry: nothing
                # writes rows below its pos until the slot is
                # reassigned (inactive slots only scribble AT pos,
                # which is past any matchable prefix), so an ended
                # session's system prompt stays a warm donor until the
                # slot is actually reclaimed by a new admission

    def _admit_locked(self) -> List[Tuple[_EngineSession, Any, Any, int]]:
        admitted = []
        if self._draining:
            return admitted   # evacuating: no new slot occupancy
        while self._free and self._pending:
            sess = self._pending.pop(0)
            if sess.ended or sess.done:
                sess.pcache = sess.dcache = None
                continue              # ended while waiting
            slot = self._free.pop()
            sess.slot = slot
            self._slots[slot] = sess
            if sess.t_ready is not None:   # admission phase: first
                self.phase_s["admission"] += \
                    time.monotonic() - sess.t_ready  # token -> slot
            if self._prefix is not None:
                # slot reclaim IS the eviction point: the insert below
                # replaces whatever prefix the slot advertised before
                # (its rows are about to be overwritten by
                # cache_insert_slot)
                self._prefix.evict(slot)
                if sess.ptoks:
                    self._prefix.insert(sess.ptoks, slot)
            admitted.append((sess, sess.pcache, sess.dcache, slot))
            sess.pcache = sess.dcache = None
        return admitted

    def _collect_locked(self) -> List[_EngineSession]:
        """Slots decoding THIS step: live sessions with queue room.
        A draining engine stops stepping — every live session is being
        handed to a healthy replica, and the replay there regenerates
        anything this engine would have decoded."""
        if self._draining:
            return []
        return [s for s in self._slots.values()
                if not s.done and
                len(s.queue) < self.ecfg.token_queue_depth]

    def _maybe_push_metrics(self, force: bool = False) -> None:
        """Fire-and-forget occupancy/waiting/prefix sample to this
        worker's nodelet (``serve_metrics`` notify): the nodelet folds
        it into per-(deployment, replica) gauges in its OWN registry,
        which the metrics-history ring samples — that is how engine
        occupancy becomes the per-deployment time series the autoscale
        loop and ``ray-tpu top`` read (worker registries are never
        scraped directly).  Engine thread only; never blocks on the
        RPC."""
        from ..core.config import GlobalConfig
        iv = getattr(GlobalConfig, "serve_engine_metrics_interval_s", 0.5)
        if iv is None or iv <= 0:
            return
        now = time.monotonic()
        if not force and now - self._last_metrics_push < iv:
            return
        self._last_metrics_push = now
        # every key here is folded by nodelet._h_serve_metrics (the
        # rpc-payload-contract rule flags unread wire bytes); prefix
        # counters travel cumulative and the nodelet folds the delta
        payload = {"deployment": self.name, "replica": self._tag,
                   "occupied": len(self._slots),
                   "max_slots": self.ecfg.max_slots,
                   "waiting": len(self._pending) + len(self._prefilling),
                   "prefix_hits": self.prefix_hits,
                   "prefix_tokens_reused": self.prefix_tokens_reused,
                   # data-plane flight instruments (all cumulative;
                   # nodelet delta-folds): per-program dispatch/compile
                   # ledger + MFU, tokens generated, phase attribution,
                   # and the distinct-shape count the compile-storm
                   # detector watches
                   "tokens": self.tokens,
                   "distinct_program_shapes": len(self._shapes),
                   "device_profile": self._prof.snapshot(),
                   "phase_totals": self.phase_totals()}
        try:
            import asyncio

            from ..core.worker_runtime import current_worker_runtime
            rt = current_worker_runtime()
            if rt is not None and rt._loop is not None:
                asyncio.run_coroutine_threadsafe(
                    rt.nodelet.notify("serve_metrics", payload), rt._loop)
        except Exception:
            pass   # driver-local engine (tests) or torn-down runtime

    def _shape_seen(self, kind: str, *dims) -> None:
        """Record one dispatched program shape (engine thread only) —
        surfaces in stats() so a per-path compile storm is visible."""
        self._shapes.add((kind,) + tuple(int(d) for d in dims))

    def _prefill_advance(self, sess: _EngineSession) -> Optional[int]:
        """Run ONE fixed-shape chunk program of a joining session's
        prompt (target + draft when speculating) on the engine thread —
        interleaved between shared decode steps, so admission stalls
        live streams by at most one chunk interval instead of a whole
        prompt.  Returns the session's first token once the prompt is
        fully consumed, else None."""
        import jax.numpy as jnp

        from ..core.runtime_metrics import SERVE_PREFILL_CHUNKS
        from ..models import init_kv_cache
        from ..util import tracing
        if sess.pcache is None:
            seeded = False
            if self._prefix is not None and sess.ptoks:
                # shared-prefix admission: the longest prefix this
                # prompt shares with a LIVE slot's prompt is already in
                # the slot cache — copy those K/V rows (one compiled
                # gather, slot + depth traced) and prefill only the
                # unshared suffix.  Cap at len-1: the last prompt
                # token's logits must be recomputed to emit the first
                # token.
                donor, depth = self._prefix.longest_match(
                    sess.ptoks, cap=len(sess.ptoks) - 1)
                # an indexed donor is valid whether its session is
                # still decoding or ended: entries are only replaced
                # when the slot is reassigned, and freed slots' rows
                # below the match depth are never written in between
                if donor is not None and \
                        depth >= max(1, self.ecfg.prefix_cache_min_tokens):
                    from ..core.runtime_metrics import (
                        SERVE_PREFIX_HITS, SERVE_PREFIX_TOKENS_REUSED)
                    sess.pcache = self._gather(self._cache,
                                               jnp.int32(donor),
                                               jnp.int32(depth))
                    if self._spec:
                        sess.dcache = self._gather(self._dcache,
                                                   jnp.int32(donor),
                                                   jnp.int32(depth))
                    sess.poff = depth
                    seeded = True
                    with self._cond:   # stats() reads these counters
                        self.prefix_hits += 1
                        self.prefix_tokens_reused += depth
                    self._shape_seen("prefix_gather", 1)
                    SERVE_PREFIX_HITS.inc(tags={"deployment": self.name})
                    SERVE_PREFIX_TOKENS_REUSED.inc(
                        depth, tags={"deployment": self.name})
            if not seeded:
                sess.pcache = init_kv_cache(self.cfg, 1, self.max_len)
                if self._spec:
                    sess.dcache = init_kv_cache(self._draft_cfg, 1,
                                                self.max_len)
        chunk = max(1, int(self.ecfg.prefill_chunk_tokens))
        n = int(sess.prompt.shape[1])
        off = sess.poff
        take = chunk if n - off >= chunk else 1
        toks = sess.prompt[:, off:off + take]
        if sess.t_pf is None:          # queue phase ends at the first
            sess.t_pf = time.monotonic()  # chunk program of the prompt
            self.phase_s["queue"] += sess.t_pf - sess.t_enq
        t0 = time.time()
        sess.plogits, sess.pcache = self._chunk(self.params, toks,
                                                sess.pcache, cfg=self.cfg)
        self._shape_seen("prefill_chunk", 1, take)
        if self._spec:
            _, sess.dcache = self._chunk(self._draft_params, toks,
                                         sess.dcache,
                                         cfg=self._draft_cfg)
            self._shape_seen("draft_prefill_chunk", 1, take)
        sess.poff = off + take
        self._prof.note_tokens("prefill_chunk", take)
        with self._cond:   # stats() reads this counter
            self.prefill_chunks += 1
        SERVE_PREFILL_CHUNKS.inc(tags={"deployment": self.name})
        tracing.record_span(f"serve_prefill_chunk::{self.name}", "serve",
                            t0, time.time(), tokens=take,
                            deployment=self.name)
        if sess.poff < n:
            return None
        return int(jnp.argmax(sess.plogits, axis=-1)
                   .astype(jnp.int32)[0])

    def _spec_step(self, tokens, active, fi):
        """One speculative iteration over the whole batch: the draft
        proposes ``spec_k`` tokens per slot in one scanned dispatch and
        the target verifies all of them (plus one bonus token) in one
        k+1-wide batched forward.  Returns host arrays
        ``(greedy [S, k+1], accepted [S])``; raises on any draft/verify
        fault (the loop falls back to a plain step — a broken draft can
        slow a stream, never corrupt it)."""
        import numpy as np

        import jax.numpy as jnp
        if fi.ACTIVE is not None:
            act = fi.ACTIVE.point("serve.spec_verify", self.name)
            if act is not None:
                if act["action"] in ("delay", "latency"):
                    time.sleep(max(0.0, act["delay_s"]))
                else:
                    raise RuntimeError(
                        f"chaos: injected spec_verify failure for "
                        f"{self.name}")
        tok_dev = jnp.asarray(tokens)
        active_dev = jnp.asarray(active)
        # the draft cache's pos is re-synced from the target every
        # iteration: its rejected speculative writes sit past the true
        # pos and are rewritten before any masked read
        dcache = {"k": self._dcache["k"], "v": self._dcache["v"],
                  "pos": self._cache["pos"]}
        # the draft scans spec_k steps but only spec_k - 1 proposals are
        # verified: the k-th step's K/V WRITE is what matters — on a
        # fully-accepted iteration the last emitted token's row must
        # already be in the draft cache, or every later proposal chain
        # attends a hole and acceptance collapses
        props, dcache = self._draft(self._draft_params, tok_dev, dcache,
                                    active_dev, cfg=self._draft_cfg,
                                    k=self._spec_k)
        self._shape_seen("draft_propose", len(tokens), self._spec_k)
        props = props[:, :self._spec_k - 1]
        fed = jnp.concatenate([tok_dev[:, None], props], axis=1)
        greedy_dev, acc_dev, new_cache = self._verify(
            self.params, fed, props, self._cache, active_dev,
            cfg=self.cfg)
        self._shape_seen("verify", len(tokens), self._spec_k)
        # materialize BEFORE committing the caches: an async device
        # fault surfaces here and leaves the pre-spec state untouched
        greedy = np.asarray(greedy_dev)
        accepted = np.asarray(acc_dev)
        self._cache = new_cache
        self._dcache = dcache
        return greedy, accepted

    def _loop(self) -> None:
        import numpy as np

        import jax.numpy as jnp

        from ..core.runtime_metrics import (SERVE_DECODE_OCCUPANCY,
                                            SERVE_SPEC_ACCEPTANCE,
                                            SERVE_SPEC_ACCEPTED,
                                            SERVE_SPEC_PROPOSED,
                                            SERVE_TOKENS)
        from ..models import init_slot_cache
        from ..util import fault_injection as fi
        from ..util import tracing
        if self._cache is None:
            self._cache = init_slot_cache(self.cfg, self.ecfg.max_slots,
                                          self.max_len)
            if self._spec:
                self._dcache = init_slot_cache(
                    self._draft_cfg, self.ecfg.max_slots, self.max_len)
        tokens = np.zeros(self.ecfg.max_slots, np.int32)
        tok_dev = None       # device-resident step output → next input
        active_dev = None
        active_key: Any = None
        while True:
            with self._cond:
                while not self._shutdown:
                    self._reap_locked()
                    self._maybe_push_metrics()
                    self._prefilling = [
                        s for s in self._prefilling
                        if not (s.ready or s.done or s.ended or s.shed)]
                    admitted = self._admit_locked()
                    prefills = ([] if self._draining
                                else list(self._prefilling))
                    batch = self._collect_locked()
                    if admitted or prefills or batch:
                        break
                    self._cond.wait(0.5)
                if self._shutdown:
                    return
                active = np.zeros(self.ecfg.max_slots, bool)
                for s in batch:
                    active[s.slot] = True
                    tokens[s.slot] = s.last_tok
            # ---- device work, OUTSIDE the lock (nobody else touches
            # the slot cache, and client ops must not stall on compute)
            t0 = time.time()
            for sess, pcache, dcache, slot in admitted:
                self._cache = self._insert(self._cache, pcache,
                                           jnp.int32(slot))
                if self._spec and dcache is not None:
                    self._dcache = self._insert(self._dcache, dcache,
                                                jnp.int32(slot))
            # one chunk program per joining session per iteration: the
            # prompt is consumed BETWEEN decode steps, never ahead of
            # the live batch
            ready: List[Tuple[_EngineSession, int]] = []
            for sess in prefills:
                try:
                    first = self._prefill_advance(sess)
                    if first is not None:
                        ready.append((sess, first))
                except Exception as e:
                    with self._cond:
                        sess.error = f"chunked prefill failed: {e!r}"
                        sess.done = True
                        sess.ready = True
                        sess.pcache = sess.dcache = sess.plogits = None
                        self._cond.notify_all()
            if ready:
                now_mono = time.monotonic()
                now_wall = time.time()
                with self._cond:
                    for sess, first in ready:
                        sess.t_ready = now_mono
                        # per-request admission span (wall clock, like
                        # every lifecycle span): enqueue -> first token
                        tracing.record_span(
                            f"serve_admission::{self.name}", "serve",
                            now_wall - (now_mono - sess.t_enq),
                            now_wall, rid=sess.rid, sid=sess.sid,
                            deployment=self.name)
                        sess.last_tok = first
                        sess.pos = sess.poff
                        sess.ready = True
                        sess.prompt = sess.plogits = None
                        if sess.pos >= self.max_len or sess.ended:
                            sess.done = True  # nothing left to decode
                            sess.pcache = sess.dcache = None
                        else:
                            self._pending.append(sess)
                    self._cond.notify_all()
            if not batch:
                continue          # admissions/prefill only: step next round
            spec_out = None
            if self._spec and not self._spec_disabled:
                try:
                    spec_out = self._spec_step(tokens, active, fi)
                    self._spec_fail_streak = 0
                    tok_dev = None   # host owns the carry again
                except Exception as e:
                    with self._cond:   # stats() reads these
                        self.spec_fallbacks += 1
                        self._spec_fail_streak += 1
                        if self._spec_fail_streak >= \
                                max(1, self.ecfg.spec_fail_disable):
                            self._spec_disabled = True
                    tracing.record_span(
                        f"serve_spec_fallback::{self.name}", "serve",
                        t0, time.time(), error=repr(e),
                        deployment=self.name)
                    tok_dev = None   # degrade to the plain step below
            if spec_out is None:
                try:
                    if admitted or tok_dev is None or \
                            active_key != tuple(active):
                        # membership changed: re-upload the [S]
                        # token/mask rows; on a steady batch the step
                        # output feeds the next step from device memory
                        tok_dev = jnp.asarray(tokens)
                        active_dev = jnp.asarray(active)
                        active_key = tuple(active)
                    tok_dev, self._cache = self._step(
                        self.params, tok_dev, self._cache, active_dev,
                        cfg=self.cfg)
                    self._shape_seen("decode_step", len(tokens))
                    new_toks = np.asarray(tok_dev)
                    tokens[:] = new_toks
                except Exception as e:             # pragma: no cover
                    with self._cond:
                        for s in batch:
                            s.error = f"decode engine step failed: {e!r}"
                            s.done = True
                        self._cond.notify_all()
                    tok_dev = None
                    continue
            occupancy = len(batch)
            # MFU numerators: useful tokens only (active slots), host-
            # known counts — never a device sync
            if spec_out is not None:
                self._prof.note_tokens("draft_propose",
                                       occupancy * self._spec_k)
                self._prof.note_tokens("verify",
                                       occupancy * self._spec_k)
            else:
                self._prof.note_tokens("decode_step", occupancy)
            now = time.time()
            if spec_out is not None:
                greedy, accepted = spec_out
                emitted = int(sum(accepted[s.slot] for s in batch))
                tracing.record_span(
                    f"serve_spec_verify::{self.name}", "serve", t0, now,
                    batch=occupancy, proposed=(self._spec_k - 1) * occupancy,
                    emitted=emitted, deployment=self.name)
            else:
                emitted = occupancy
                tracing.record_span(f"serve_decode_step::{self.name}",
                                    "serve", t0, now,
                                    batch=occupancy,
                                    deployment=self.name)
            SERVE_DECODE_OCCUPANCY.observe(occupancy,
                                           {"deployment": self.name})
            SERVE_TOKENS.inc(emitted, {"deployment": self.name})
            with self._cond:
                self.steps += 1
                self.tokens += emitted
                if spec_out is not None:
                    greedy, accepted = spec_out
                    for s in batch:
                        n = int(accepted[s.slot])
                        row = greedy[s.slot]
                        toks = [int(row[i]) for i in range(n)]
                        s.last_tok = toks[-1]
                        tokens[s.slot] = s.last_tok
                        s.pos += n
                        if not s.ended:
                            s.queue.extend(toks)
                        if s.pos >= self.max_len:
                            s.done = True
                    self.spec_proposed += (self._spec_k - 1) * occupancy
                    self.spec_accepted += emitted - occupancy
                else:
                    for s in batch:
                        tok = int(new_toks[s.slot])
                        s.last_tok = tok
                        s.pos += 1
                        if not s.ended:
                            s.queue.append(tok)
                        if s.pos >= self.max_len:
                            s.done = True  # cache full: reaped next turn
                self._cond.notify_all()
            if spec_out is not None:
                SERVE_SPEC_PROPOSED.inc((self._spec_k - 1) * occupancy,
                                        {"deployment": self.name})
                SERVE_SPEC_ACCEPTED.inc(emitted - occupancy,
                                        {"deployment": self.name})
                if self.spec_proposed:
                    SERVE_SPEC_ACCEPTANCE.set(
                        self.spec_accepted / self.spec_proposed,
                        {"deployment": self.name})


def _host_tokens(prompt) -> Optional[tuple]:
    """Prompt ints straight from the request payload (the prefix-index
    key) — no device round trip.  Returns None when the payload isn't a
    host-side B=1 token list (device arrays fall back to start()'s own
    materialization)."""
    if not isinstance(prompt, (list, tuple)):
        return None
    try:
        p = prompt
        if p and isinstance(p[0], (list, tuple)):
            p = p[0]
        return tuple(int(t) for t in p)
    except (TypeError, ValueError, IndexError):
        return None


class DecodeSessionCore:
    """Session store + compiled prefill/decode over one model.

    Protocol (msgpack/JSON-native):
      {"op": "start", "prompt": [S ints] | [[S ints]xB]} ->
          {"sid": str|int, "token": [B ints]} (+ {"proto": "chunk",
          "seq": 0} when the continuous-batching engine owns the
          session)
      {"op": "resume", "prompt": [S ints], "generated": [G ints]} ->
          same shape as an engine start, with "seq": G — failover
          re-admission: teacher-forced prefix prefill of
          prompt+generated into a fresh engine slot; the returned token
          is exactly the one the uninterrupted session would have
          produced next (greedy decode is deterministic)
      {"op": "next", "sid": ...} -> {"token": [B ints]}
      {"op": "next_chunk", "sid": str, "max_tokens": N} ->
          {"tokens": [<=N ints], "done": bool, "seq": first token's
          seq} (+ {"migrating": true} when the replica is draining and
          the session must be resumed elsewhere)
      {"op": "end", "sid": ...} -> {"ended": bool}
      {"op": "stats"} -> engine/session counters (tests, dashboards)

    Engine sessions (single-prompt starts, the serving hot path) carry
    STRING sids of the form ``<replica_tag>:<n>`` — the prefix is the
    owning replica, which the proxy/router use for sid-sticky routing.
    Batched (B>1) prompts on an engine core are admitted row-by-row as
    engine sessions behind a ``grp:<n>`` sid that keeps the legacy
    reply shape.  Only ``engine=False`` cores (non-LM deployments, the
    parity oracle in tests) still run the eager integer-sid path:
    pop-as-lease (a pipelined second `next` on the SAME sid — or a
    stale/unknown sid — gets an ``{"error": ...}`` reply instead of
    racing the first), LRU-bounded ``max_sessions``.
    """

    def __init__(self, cfg, max_len: int, seed: int = 0,
                 params: Any = None, max_sessions: int = 64,
                 prefill_chunk: int = 0,
                 engine: Any = True):
        """``prefill_chunk > 0`` prefills in fixed-size chunks through
        one small reusable program instead of a whole-prompt compile —
        for models whose full-prompt flash prefill is a compile-helper
        killer (llama-family GQA, SURVEY §9); it also overrides the
        engine's ``prefill_chunk_tokens`` so the legacy path and the
        engine's chunked admission share one chunk shape.  ``engine``
        is True (default), False, or a :class:`DecodeEngineConfig`."""
        import jax

        from ..models import init_params
        self.cfg = cfg
        self.max_len = max_len
        self.max_sessions = max_sessions
        if params is None:
            params, _ = init_params(jax.random.PRNGKey(seed), cfg)
        self.params = params
        self._lock = threading.Lock()
        self.sessions: Dict[int, Any] = {}   # insertion-ordered = LRU
        self._next_sid = 0
        # B>1 prompt batches on an engine core: each row is its own
        # engine session; the group keeps the legacy one-reply-per-step
        # protocol shape (sid + [B] tokens) over the SINGLE data plane
        self._groups: Dict[str, List[str]] = {}
        self._next_gid = 0
        if engine is False or engine is None:
            self._engine_cfg = None
        elif isinstance(engine, DecodeEngineConfig):
            self._engine_cfg = engine
        else:
            self._engine_cfg = DecodeEngineConfig()
        if self._engine_cfg is None:
            # the eager per-call path survives ONLY as the explicit
            # opt-out (`engine=False`): non-LM deployments and the
            # parity oracle in tests.  Engine cores never compile the
            # whole-prompt prefill or the batch-1 decode step at all —
            # exactly one decode data plane per replica.
            from ..models import decode_step, prefill, prefill_chunked
            if prefill_chunk > 0:
                def chunked(params, prompt, *, cfg, cache):
                    return prefill_chunked(params, prompt, cfg, cache,
                                           chunk=prefill_chunk)

                self._prefill = chunked
            else:
                self._prefill = jax.jit(prefill, static_argnames=("cfg",))
            self._decode = jax.jit(decode_step, static_argnames=("cfg",))
        if self._engine_cfg is not None and prefill_chunk > 0:
            # one chunk width per replica: the engine's admission/resume
            # programs and the legacy prefill_chunked path must share
            # shapes, or each path compiles its own chunk program
            import dataclasses as _dc
            self._engine_cfg = _dc.replace(
                self._engine_cfg, prefill_chunk_tokens=prefill_chunk)
        self._engine: Optional[ContinuousBatchingEngine] = None

    @property
    def engine(self) -> Optional[ContinuousBatchingEngine]:
        """The continuous-batching engine, created on first use (slot
        cache memory is only paid by cores that actually serve).
        Creation is locked: two concurrent `start` ops racing the lazy
        init would strand one session in an engine nothing references
        — and hand out colliding ``<tag>:0`` sids."""
        if self._engine is None and self._engine_cfg is not None:
            with self._lock:
                if self._engine is None:
                    name, tag = "decode", "local"
                    try:
                        from .replica import get_replica_context
                        ctx = get_replica_context()
                        name, tag = ctx.deployment, ctx.replica_tag
                    except RuntimeError:
                        pass
                    self._engine = ContinuousBatchingEngine(
                        self.cfg, self.max_len, self.params,
                        self._engine_cfg, name=name, replica_tag=tag)
        return self._engine

    def handle(self, req: Dict[str, Any]) -> Dict[str, Any]:
        import jax.numpy as jnp

        from ..models import init_kv_cache
        op = req["op"]
        if op == "start":
            prompt = jnp.asarray(req["prompt"], jnp.int32)
            if prompt.ndim == 1:
                prompt = prompt[None]
            if self._engine_cfg is not None:
                if prompt.shape[0] == 1:
                    return self.engine.start(
                        prompt, self.max_sessions,
                        ptoks=_host_tokens(req["prompt"]),
                        rid=str(req.get("_rid") or ""))
                return self._group_start(prompt, req["prompt"])
            cache = init_kv_cache(self.cfg, prompt.shape[0],
                                  self.max_len)
            logits, cache = self._prefill(self.params, prompt,
                                          cfg=self.cfg, cache=cache)
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            with self._lock:
                sid = self._next_sid
                self._next_sid += 1
                self.sessions[sid] = (cache, tok)
                while len(self.sessions) > self.max_sessions:
                    self.sessions.pop(next(iter(self.sessions)))
            return {"sid": sid, "token": tok.tolist()}
        if op == "resume":
            # failover re-admission (serve/failover.py): replay the
            # journal — prompt + every token the client already has —
            # through a teacher-forced prefix prefill into a fresh
            # engine slot, continuing seqs at len(generated)
            if self._engine_cfg is None:
                return {"error": "resume requires the continuous-"
                                 "batching engine (engine=False core)"}
            prompt = req["prompt"]
            if prompt and isinstance(prompt[0], (list, tuple)):
                prompt = prompt[0]     # batched form: engine is B=1
            generated = list(req.get("generated") or [])
            replay = list(prompt) + generated
            prefix = jnp.asarray([replay], jnp.int32)
            return self.engine.start(
                prefix, self.max_sessions, seq_base=len(generated),
                teacher_forced=True,
                ptoks=tuple(int(t) for t in replay),
                rid=str(req.get("_rid") or ""))
        if op == "stats":
            out = {"legacy_sessions": len(self.sessions),
                   "groups": len(self._groups)}
            if self._engine is not None:
                out["engine"] = self._engine.stats()
            return out
        sid = req.get("sid")
        if isinstance(sid, str) and sid.startswith("grp:"):
            return self._group_op(op, sid)
        if op == "end":
            if isinstance(sid, str):
                if self._engine is None:
                    return {"ended": False}
                return {"ended": self._engine.end(sid)}
            with self._lock:
                return {"ended":
                        self.sessions.pop(sid, None) is not None}
        if op == "next_chunk":
            if not isinstance(sid, str) or self._engine is None:
                # legacy sessions have no token queue: one step per call
                out = self._legacy_next(sid)
                if "error" in out:
                    return out
                return {"tokens": out["token"], "done": False}
            return self._engine.next_chunk(
                sid, req.get("max_tokens", 16), req.get("timeout_s"))
        # op == "next"
        if isinstance(sid, str) and self._engine is not None:
            out = self._engine.next_chunk(sid, 1)
            if "error" in out:
                return out
            if not out["tokens"]:
                return {"error": f"session {sid!r} finished "
                                 f"(cache capacity reached)"}
            reply = {"token": out["tokens"]}
            if out["done"]:
                reply["eos"] = True
            return reply
        return self._legacy_next(sid)

    def _group_start(self, prompt, raw_prompt=None) -> Dict[str, Any]:
        """B>1 prompts through the ONE data plane: admit each row as
        its own engine session and hand back a group sid whose `next`
        pops one token per member — the legacy per-call protocol shape
        ({sid, token: [B]}) without the legacy prefill/decode programs.
        A member shed mid-admission (slots + wait queue full) releases
        the members already admitted and re-raises, so a group is all
        or nothing."""
        sids, toks = [], []
        try:
            for row in range(int(prompt.shape[0])):
                pt = None
                if raw_prompt is not None:
                    try:
                        pt = _host_tokens([raw_prompt[row]])
                    except (TypeError, IndexError):
                        pt = None
                out = self.engine.start(prompt[row:row + 1],
                                        self.max_sessions, ptoks=pt)
                sids.append(out["sid"])
                toks.extend(out["token"])
        except BaseException:
            for s in sids:
                self.engine.end(s)
            raise
        with self._lock:
            gid = f"grp:{self._next_gid}"
            self._next_gid += 1
            self._groups[gid] = sids
        return {"sid": gid, "token": toks}

    def _group_op(self, op: str, gid: str) -> Dict[str, Any]:
        with self._lock:
            sids = self._groups.get(gid)
        if sids is None or self._engine is None:
            return {"error": f"unknown session {gid!r} (ended, "
                             f"evicted, or never started)"}
        if op == "end":
            for s in sids:
                self._engine.end(s)
            with self._lock:
                self._groups.pop(gid, None)
            return {"ended": True}
        # op in ("next", "next_chunk"): one decode step for every
        # member (rows share a prompt length, so they reach the cache
        # cap together, like the legacy shared-pos batch did)
        toks = []
        for s in sids:
            out = self._engine.next_chunk(s, 1)
            if "error" in out:
                return out
            if not out["tokens"]:
                return {"error": f"session {gid!r} finished "
                                 f"(cache capacity reached)"}
            toks.extend(out["tokens"])
        if op == "next_chunk":
            return {"tokens": toks, "done": False}
        return {"token": toks}

    def _legacy_next(self, sid) -> Dict[str, Any]:
        import jax.numpy as jnp
        with self._lock:
            entry = self.sessions.pop(sid, None)
        if entry is None:
            return {"error": f"unknown session {sid!r} (ended, "
                             f"evicted, or decoding in another request)"}
        cache, tok = entry
        logits, cache = self._decode(self.params, tok, cache,
                                     cfg=self.cfg)
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        with self._lock:
            self.sessions[sid] = (cache, tok)
        return {"token": tok.tolist()}
