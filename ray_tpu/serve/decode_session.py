"""Stateful KV-cache decode sessions for Serve replicas.

The serving-side face of the model runtime (reference: Ray Serve
delegates streaming decode to external engines like vLLM —
/root/reference/doc/source/serve/index.md; here it is in-tree): a
replica holds per-session KV caches so `start` pays one prefill and
every `next` is a single decode step.  Used by the streaming-decode
example and `bench.py --serve`; wrap it in a `@serve.deployment` whose
``__call__`` forwards to :meth:`handle`.

prefill/decode compile ONCE per replica (config static, cache position
dynamic) — eager per-step dispatch costs ~100x on small models, which
the round-4 TTFT benchmark measured directly (700 ms → 4.8 ms/token).
"""

from __future__ import annotations

import threading
from typing import Any, Dict


class DecodeSessionCore:
    """Session store + compiled prefill/decode over one model.

    Protocol (msgpack/JSON-native):
      {"op": "start", "prompt": [S ints] | [[S ints]xB]} ->
          {"sid": int, "token": [B ints]}
      {"op": "next", "sid": int} -> {"token": [B ints]}
    Sessions are popped while decoding (pop-as-lease), so concurrent
    `next` calls on ONE session serialize by construction.
    """

    def __init__(self, cfg, max_len: int, seed: int = 0,
                 params: Any = None):
        import jax

        from ..models import decode_step, init_params, prefill
        self.cfg = cfg
        self.max_len = max_len
        if params is None:
            params, _ = init_params(jax.random.PRNGKey(seed), cfg)
        self.params = params
        self._prefill = jax.jit(prefill, static_argnames=("cfg",))
        self._decode = jax.jit(decode_step, static_argnames=("cfg",))
        self._lock = threading.Lock()
        self.sessions: Dict[int, Any] = {}
        self._next_sid = 0

    def handle(self, req: Dict[str, Any]) -> Dict[str, Any]:
        import jax.numpy as jnp

        from ..models import init_kv_cache
        if req["op"] == "start":
            prompt = jnp.asarray(req["prompt"], jnp.int32)
            if prompt.ndim == 1:
                prompt = prompt[None]
            cache = init_kv_cache(self.cfg, prompt.shape[0],
                                  self.max_len)
            logits, cache = self._prefill(self.params, prompt,
                                          cfg=self.cfg, cache=cache)
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            with self._lock:
                sid = self._next_sid
                self._next_sid += 1
                self.sessions[sid] = (cache, tok)
            return {"sid": sid, "token": tok.tolist()}
        with self._lock:
            cache, tok = self.sessions.pop(req["sid"])
        logits, cache = self._decode(self.params, tok, cache,
                                     cfg=self.cfg)
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        with self._lock:
            self.sessions[req["sid"]] = (cache, tok)
        return {"token": tok.tolist()}
