"""Gang replicas: one Serve replica that SPANS multiple processes/hosts.

SURVEY.md §7 hard-part (5) and the BASELINE north star #5: a replica that
*is* a multi-host sharded program.  The reference has nothing like this —
its replica is one actor (`serve/_private/replica.py:250`), and its
reconcile loop (`serve/_private/deployment_state.py:958`) only manages
single-process replicas.  TPU-native serving of a TP-sharded model needs a
*gang*: one worker per TPU host, all joined into one `jax.distributed`
runtime, hosting ONE pjit program whose shards live across the gang.

Design:

  * the controller reserves a placement group (one bundle per gang member;
    `tpu_slice_placement_group` shape for TPU slices) and spawns
    ``gang_size`` `GangReplicaWorker` actors into it,
  * every member joins a mesh gang (`parallel.coordinator.join_mesh_gang`
    — controller-KV rendezvous → `jax.distributed.initialize` → one global
    `Mesh` spanning the members' devices),
  * the member whose gang rank is 0 is the **leader**: the routing table
    entry for the replica carries only the leader's handle, so the router
    addresses the whole gang as one unit (in-flight caps, round-robin, and
    autoscaling all see one replica),
  * `handle_request` on the leader fans the request out to the followers
    and executes its own shard; every member enters the same jitted
    computation and XLA's collectives (ICI on TPU, Gloo on the CPU test
    mesh) rendezvous the gang inside the program.  The leader's return
    value (replicated or leader-addressable out_shardings) answers the
    request.

The user callable reads its gang context (mesh, rank, world size) via
`get_gang_context()` in ``__init__`` and pjit-shards its model over
``ctx.mesh``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

_CTX: Optional["GangContext"] = None


@dataclasses.dataclass
class GangContext:
    """What a deployment callable sees when it runs inside a gang."""

    mesh: Any                 # jax.sharding.Mesh spanning the gang
    rank: int                 # this member's gang rank (0 = leader)
    world_size: int
    group_name: str
    deployment_name: str
    replica_id: str


def get_gang_context() -> Optional[GangContext]:
    """The current gang context, or None outside a gang replica."""
    return _CTX


class GangReplicaWorker:
    """One member of a gang replica.  Rank 0 doubles as the leader."""

    def __init__(self, deployment_name: str, replica_id: str, rank: int,
                 world_size: int, group_name: str, callable_blob: bytes,
                 init_args: tuple, init_kwargs: Dict[str, Any],
                 user_config: Any, mesh_text: Optional[str]):
        global _CTX
        import inspect

        from ..core.serialization import loads_function
        from ..parallel.coordinator import join_mesh_gang
        from ..parallel.mesh import MeshSpec

        self.deployment_name = deployment_name
        self.replica_id = replica_id
        self.rank = rank
        self.world_size = world_size
        self._group_name = group_name
        self._peers: List[Any] = []   # leader only: follower handles
        spec = MeshSpec.parse(mesh_text) if mesh_text else None
        mesh = join_mesh_gang(group_name, world_size, rank=rank, spec=spec)
        _CTX = GangContext(mesh=mesh, rank=rank, world_size=world_size,
                           group_name=group_name,
                           deployment_name=deployment_name,
                           replica_id=replica_id)
        fc = loads_function(callable_blob)
        if inspect.isclass(fc):
            self._callable = fc(*init_args, **init_kwargs)
            self._is_function = False
        else:
            self._callable = fc
            self._is_function = True
        self._num_ongoing = 0
        self._total = 0
        # SPMD ordering machinery: every member must enter the compiled
        # program in the same request order or the collectives cross-match.
        # The leader serializes (lock held across fan-out + own execute, so
        # its send order IS its execution order); followers execute strictly
        # by the leader-assigned sequence number.
        import threading
        self._exec_lock = threading.Lock()
        self._seq = 0
        self._next_seq = 0
        self._num_executing = 0
        self._seq_cv = threading.Condition()
        if user_config is not None:
            self.reconfigure(user_config)

    # -- wiring ------------------------------------------------------------
    def set_peers(self, handles: List[Any]) -> bool:
        """Leader only: handles of ranks 1..world_size-1, in rank order."""
        self._peers = handles
        return True

    def ready(self) -> bool:
        return True

    def reconfigure(self, user_config: Any) -> bool:
        target = self._callable
        if not self._is_function and hasattr(target, "reconfigure"):
            target.reconfigure(user_config)
        return True

    # -- request path ------------------------------------------------------
    def handle_request(self, args: tuple, kwargs: Dict[str, Any],
                       method: Optional[str] = None) -> Any:
        """Leader entry point: fan out to followers, compute own shard.

        Followers are invoked asynchronously BEFORE the leader executes so
        all members enter the jitted program (whose collectives block until
        the whole gang arrives).  Per-caller actor ordering guarantees every
        member sees requests in the same sequence — the SPMD requirement."""
        from .. import api
        self._num_ongoing += 1
        self._total += 1
        try:
            with self._exec_lock:
                seq = self._seq
                self._seq += 1
                futs = [p.participate.remote(seq, args, kwargs, method)
                        for p in self._peers]
                result = self._execute(args, kwargs, method)
            # Surface follower failures (a dead member means the gang's
            # program can no longer run; the controller replaces the whole
            # replica).
            api.get(futs, timeout=300.0)
            return result
        finally:
            self._num_ongoing -= 1

    def participate(self, seq: int, args: tuple, kwargs: Dict[str, Any],
                     method: Optional[str]) -> bool:
        """Follower side of one request: run the same computation, strictly
        in leader-assigned sequence order (concurrent actor threads would
        otherwise race into the collectives out of order)."""
        import time as _time
        with self._seq_cv:
            # The deadline bounds *stall*, not total wait: it resets while
            # _next_seq advances AND while an earlier request of this gang
            # member is still executing (a single long request — compile,
            # long-context generation — is progress, not a gap).  Only a
            # true fan-out gap (nothing running, nothing advancing for the
            # full window) trips it.
            from ..core.config import GlobalConfig
            stall_s = GlobalConfig.serve_gang_stall_timeout_s
            deadline = _time.monotonic() + stall_s
            last_seen = self._next_seq
            while seq != self._next_seq:
                if self._next_seq != last_seen or self._num_executing > 0:
                    last_seen = self._next_seq
                    deadline = _time.monotonic() + stall_s
                if _time.monotonic() > deadline:
                    # a gap in the sequence (leader failed mid-fan-out):
                    # fail loudly instead of wedging this thread forever
                    raise RuntimeError(
                        f"gang member {self.rank} stuck waiting for seq "
                        f"{self._next_seq} (got {seq}); leader fan-out "
                        "gap — replica needs replacement")
                self._seq_cv.wait(timeout=30.0)
            self._num_executing += 1
        try:
            self._execute(args, kwargs, method)
        finally:
            with self._seq_cv:
                self._num_executing -= 1
                self._next_seq = seq + 1
                self._seq_cv.notify_all()
        return True

    def _execute(self, args: tuple, kwargs: Dict[str, Any],
                 method: Optional[str]) -> Any:
        import asyncio
        import inspect
        target = self._callable
        if not self._is_function and method:
            target = getattr(target, method)
        elif not self._is_function:
            target = target.__call__
        result = target(*args, **kwargs)
        if inspect.iscoroutine(result):
            result = asyncio.run(result)
        return result

    def stats(self) -> Dict[str, Any]:
        return {"replica_id": self.replica_id, "rank": self.rank,
                "world_size": self.world_size,
                "num_ongoing": self._num_ongoing, "total": self._total}

    def shutdown_gang(self) -> bool:
        from ..parallel.coordinator import leave_mesh_gang
        try:
            leave_mesh_gang(self._group_name)
        except Exception:
            pass
        return True


def start_gang_replica(name: str, rid: str, entry: Dict[str, Any],
                       cfg: Dict[str, Any]) -> Dict[str, Any]:
    """Controller-side: materialize one gang replica.

    Reserves the PG, spawns the members bundle-by-bundle, wires leader →
    followers, and blocks until every member finished its mesh join (the
    deployment is not routable before the program can run).  Returns the
    replica record for the routing table: ``handle`` is the LEADER."""
    from .. import api
    from ..util.placement_group import placement_group

    gang_size = int(cfg.get("gang_size", 1))
    strategy = cfg.get("gang_strategy", "PACK")
    opts = dict(cfg.get("ray_actor_options") or {})
    bundle_res = {"CPU": float(opts.get("num_cpus", 1.0))}
    for k, v in (opts.get("resources") or {}).items():
        bundle_res[k] = float(v)
    pg = placement_group([dict(bundle_res) for _ in range(gang_size)],
                         strategy=strategy, name=f"serve_gang_{rid}")
    pg.ready(timeout_seconds=120.0)

    group_name = f"serve_gang_{rid}"
    members = []
    for rank in range(gang_size):
        handle = api.remote(GangReplicaWorker).options(
            max_concurrency=int(cfg.get("max_concurrent_queries", 8)) + 4,
            num_cpus=bundle_res["CPU"],
            resources={k: v for k, v in bundle_res.items() if k != "CPU"},
            placement_group=pg, placement_group_bundle_index=rank,
            runtime_env=opts.get("runtime_env"),
            lifetime="detached",  # serve owns the lifecycle, not the job
        ).remote(name, rid, rank, gang_size, group_name,
                 entry["callable_blob"], entry["init_args"],
                 entry["init_kwargs"], cfg.get("user_config"),
                 cfg.get("gang_mesh"))
        members.append(handle)
    # Constructors run concurrently (the mesh join is a barrier); readiness
    # of all members implies jax.distributed linked the gang.
    from ..core.config import GlobalConfig
    api.get([m.ready.remote() for m in members],
            timeout=GlobalConfig.serve_gang_ready_timeout_s)
    api.get(members[0].set_peers.remote(members[1:]), timeout=60.0)
    return {"id": rid, "handle": members[0], "gang": members, "pg": pg}


def stop_gang_replica(rep: Dict[str, Any]) -> None:
    from .. import api
    from ..util.placement_group import remove_placement_group
    for m in rep.get("gang", []):
        try:
            api.kill(m)
        except Exception:
            pass
    pg = rep.get("pg")
    if pg is not None:
        try:
            remove_placement_group(pg)
        except Exception:
            pass
