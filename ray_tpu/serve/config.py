"""Serve configuration schemas (reference: `serve/config.py`)."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional


@dataclasses.dataclass
class AutoscalingConfig:
    min_replicas: int = 1
    max_replicas: int = 4
    # capacity unit of a replica WITHOUT a decode engine: router-
    # reported in-flight requests per replica (the pre-engine signal,
    # still the fallback for plain deployments).  Engine replicas use
    # their real slot capacity instead.
    target_num_ongoing_requests_per_replica: float = 2.0
    # cooldowns between applied scale decisions, per direction —
    # hysteresis in time, so bursty traffic cannot flap the fleet
    upscale_delay_s: float = 0.0
    downscale_delay_s: float = 2.0
    # -- occupancy-trend policy (serve/autoscaler.py) ---------------------
    # utilization the fleet is sized toward after a scale decision
    target_occupancy: float = 0.6
    # scale up once recent utilization crosses this watermark (or any
    # sessions are waiting for slots) — BEFORE saturation sheds
    occupancy_high: float = 0.8
    # scale down only when utilization over the whole trend window
    # stays under this watermark; the [low, high] band is the
    # hysteresis dead zone where the fleet holds steady
    occupancy_low: float = 0.3
    # look-back the policy trends over (occupancy/waiting series from
    # `state.metrics_history` or the controller's own sample ring)
    trend_window_s: float = 10.0
    # capacity weight of a replica whose node is SUSPECT (gray
    # failure): counting it at full weight hides the brownout, zero
    # would thrash on every transient quarantine.  Down-weighted
    # replicas are also first in line as scale-down victims.
    suspect_weight: float = 0.25


@dataclasses.dataclass
class HTTPOptions:
    host: str = "127.0.0.1"
    port: int = 8000
    # "HeadOnly": one proxy in the driver's node (default).
    # "EveryNode": the controller reconciles one proxy actor per alive
    #   node, each binding an ephemeral port announced in the proxy
    #   table (reference: http_state.py per-node proxy management; fixed
    #   per-node ports are impossible here because test clusters share
    #   one host/IP).
    # "NoServer": handles only, no HTTP ingress.
    location: str = "HeadOnly"


@dataclasses.dataclass
class DecodeEngineConfig:
    """Knobs of the replica-resident continuous-batching decode engine
    (`serve/decode_session.py`).  One fixed-slot batched KV cache and
    one jitted decode step are shared by every live session; these
    bounds govern admission and token buffering."""
    # decode slots in the batched KV cache — the compiled batch size.
    # Sessions beyond this wait for a slot (iteration-level admission).
    max_slots: int = 8
    # per-session bounded token queue: the engine decodes ahead of the
    # client by at most this many tokens, then pauses the slot
    token_queue_depth: int = 64
    # sessions allowed to wait for a slot before `start` is rejected
    # with ReplicaUnavailableError (→ HTTP 503 + Retry-After)
    max_waiting: int = 32
    # how long a `next_chunk` drain will linger for its chunk to fill
    # once at least one token is buffered (amortizes transport without
    # stalling slow decodes)
    chunk_linger_s: float = 0.025
    # server-side cap on one `next_chunk` wait with an empty queue
    chunk_timeout_s: float = 30.0
    # leak reaper: a session whose client has not polled (`next_chunk`)
    # for this long is evicted and its slot reclaimed — abandoned
    # streams (client crashed without `end`) must not hold decode slots
    # or session-table memory forever.  <= 0 disables the reaper.
    session_idle_ttl_s: float = 120.0
    # -- chunked-prefill admission ----------------------------------------
    # a joining session's prompt is consumed [1, chunk] tokens at a time
    # BETWEEN shared decode steps on the engine thread (remainder in
    # [1, 1] tail steps) — admission, failover resume, and the legacy
    # prefill_chunked path all reuse the same two compiled chunk shapes,
    # and a join never stalls live streams by more than one chunk
    # interval.  Matches models.resume_prefill's default so resumes and
    # admissions share programs.
    prefill_chunk_tokens: int = 32
    # bound on one `start`/`resume` call: enqueue -> first token (the
    # prompt is prefilled by the engine thread; a wedged engine must not
    # hang the caller forever — timeout sheds with the typed 503)
    admission_timeout_s: float = 60.0
    # -- shared-prefix KV reuse -------------------------------------------
    # admission consults a radix trie over live slots' prompts
    # (serve/prefix_cache.py): a new session sharing a prefix with a
    # resident slot copies those K/V rows (`models.cache_gather_slot`)
    # and chunk-prefills ONLY the unshared suffix — shared system
    # prompts skip their prefill entirely
    prefix_cache: bool = True
    # minimum shared tokens worth a gather dispatch (a 1-2 token match
    # costs more in dispatch than it saves in prefill)
    prefix_cache_min_tokens: int = 4
    # -- speculative decoding ---------------------------------------------
    # draft model proposing tokens for the target to verify in one
    # batched k-token forward.  None disables; "shared" weight-shares
    # the target (exact self-speculation — acceptance 1.0, the win is
    # dispatch amortization: 2 dispatches per k+1 tokens); a
    # (TransformerConfig, params) tuple supplies a real draft; a bare
    # TransformerConfig gets fresh seed-0 params (tests).  Greedy
    # verification is exact-match, so token streams stay byte-identical
    # to plain decode whatever the draft quality.
    spec_draft: Any = None
    # draft tokens proposed per engine iteration (the verify program is
    # k+1 tokens wide; each iteration emits 1..k+1 tokens per slot)
    spec_k: int = 4
    # consecutive draft/verify failures before the engine stops
    # speculating and stays on plain decode (each failure already falls
    # back to a plain step for that iteration — streams never corrupt)
    spec_fail_disable: int = 3


@dataclasses.dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_concurrent_queries: int = 8
    user_config: Optional[Any] = None
    autoscaling_config: Optional[AutoscalingConfig] = None
    ray_actor_options: Dict[str, Any] = dataclasses.field(
        default_factory=dict)
    version: int = 0
    # -- gang replicas (serve/gang.py): one replica spanning N processes --
    gang_size: int = 1                    # >1 → replica is a mesh gang
    gang_mesh: Optional[str] = None       # MeshSpec text, e.g. "tp=2"
    gang_strategy: str = "PACK"           # placement group strategy
