"""Serve controller: the single reconciliation authority.

Capability mirror of the reference's `ServeController`
(`serve/controller.py:61`) + `DeploymentStateManager`
(`serve/_private/deployment_state.py:958,1767`): holds target state, starts/
stops replica actors toward it, versions the routing table (long-poll
`serve/_private/long_poll.py` role: routers poll ``snapshot(version)``),
and applies the autoscaling policy on router-reported metrics
(`serve/_private/autoscaling_policy.py:93`).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional


class ServeController:
    def __init__(self):
        self._deployments: Dict[str, Dict[str, Any]] = {}
        self._version = 0
        self._replica_seq = 0

    # -- deploy / delete ----------------------------------------------------
    def deploy(self, name: str, callable_blob: bytes, init_args: tuple,
               init_kwargs: dict, config: dict,
               route_prefix: Optional[str]) -> bool:
        entry = self._deployments.get(name)
        if entry is None:
            entry = {"replicas": [], "metrics": {}, "last_scaled": 0.0}
            self._deployments[name] = entry
        entry.update(callable_blob=callable_blob, init_args=init_args,
                     init_kwargs=init_kwargs, config=dict(config),
                     route_prefix=route_prefix)
        # full restart on redeploy of code/config (simple + correct);
        # user_config-only updates go through reconfigure()
        self._scale_to(name, 0)
        self._reconcile(name)
        self._version += 1
        return True

    def reconfigure_deployment(self, name: str, user_config: Any) -> bool:
        entry = self._deployments[name]
        entry["config"]["user_config"] = user_config
        from .. import api
        api.get([m.reconfigure.remote(user_config)
                 for r in entry["replicas"]
                 for m in (r.get("gang") or [r["handle"]])], timeout=60.0)
        self._version += 1
        return True

    def delete(self, name: str) -> bool:
        if name in self._deployments:
            self._scale_to(name, 0)
            del self._deployments[name]
            self._version += 1
        return True

    def shutdown_all(self) -> bool:
        for name in list(self._deployments):
            self.delete(name)
        return True

    # -- reconciliation -----------------------------------------------------
    def _reconcile(self, name: str) -> None:
        entry = self._deployments[name]
        cfg = entry["config"]
        target = cfg["num_replicas"]
        auto = cfg.get("autoscaling_config")
        if auto:
            target = max(auto["min_replicas"],
                         min(target, auto["max_replicas"]))
            cfg["num_replicas"] = target
        self._scale_to(name, target)

    def _scale_to(self, name: str, target: int) -> None:
        from .. import api
        from .replica import ServeReplica
        entry = self._deployments[name]
        cfg = entry.get("config", {})
        gang_size = int(cfg.get("gang_size", 1) or 1)
        while len(entry["replicas"]) < target:
            self._replica_seq += 1
            rid = f"{name}#{self._replica_seq}"
            if gang_size > 1:
                # Multi-process replica: a placement-group gang hosting one
                # sharded program (serve/gang.py); the routing table carries
                # only the leader, so the router sees one unit.
                from .gang import start_gang_replica
                entry["replicas"].append(
                    start_gang_replica(name, rid, entry, cfg))
                continue
            opts = dict(cfg.get("ray_actor_options") or {})
            handle = api.remote(ServeReplica).options(
                max_concurrency=int(cfg.get("max_concurrent_queries", 8)),
                num_cpus=opts.get("num_cpus", 0.1),
            ).remote(name, rid, entry["callable_blob"],
                     entry["init_args"], entry["init_kwargs"],
                     cfg.get("user_config"))
            entry["replicas"].append({"id": rid, "handle": handle})
        while len(entry["replicas"]) > target:
            rep = entry["replicas"].pop()
            if rep.get("gang"):
                from .gang import stop_gang_replica
                stop_gang_replica(rep)
                continue
            try:
                api.kill(rep["handle"])
            except Exception:
                pass
        self._version += 1

    # -- routing state ------------------------------------------------------
    def snapshot(self, known_version: int = -1) -> Optional[dict]:
        """Routing table if newer than known_version (long-poll pull)."""
        if known_version == self._version:
            return None
        table = {}
        for name, entry in self._deployments.items():
            table[name] = {
                "route_prefix": entry.get("route_prefix"),
                "max_concurrent_queries":
                    entry["config"].get("max_concurrent_queries", 8),
                "replicas": [{"id": r["id"], "handle": r["handle"]}
                             for r in entry["replicas"]],
            }
        return {"version": self._version, "table": table}

    def list_deployments(self) -> Dict[str, dict]:
        return {name: {"num_replicas": len(e["replicas"]),
                       "route_prefix": e.get("route_prefix"),
                       "config": {k: v for k, v in e["config"].items()
                                  if k != "ray_actor_options"}}
                for name, e in self._deployments.items()}

    # -- autoscaling --------------------------------------------------------
    def report_metrics(self, name: str, ongoing_per_replica: List[int]
                       ) -> bool:
        """Router-reported in-flight counts drive the basic autoscaler."""
        entry = self._deployments.get(name)
        if entry is None:
            return False
        cfg = entry["config"]
        auto = cfg.get("autoscaling_config")
        if not auto:
            return True
        now = time.monotonic()
        n = max(len(ongoing_per_replica), 1)
        avg = sum(ongoing_per_replica) / n
        target_per = auto["target_num_ongoing_requests_per_replica"]
        desired = min(max(
            int(-(-sum(ongoing_per_replica) // target_per) or 1),
            auto["min_replicas"]), auto["max_replicas"])
        cur = len(entry["replicas"])
        delay = (auto["upscale_delay_s"] if desired > cur
                 else auto["downscale_delay_s"])
        if desired != cur and now - entry["last_scaled"] >= delay:
            entry["last_scaled"] = now
            cfg["num_replicas"] = desired
            self._scale_to(name, desired)
        return True
