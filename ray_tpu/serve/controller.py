"""Serve controller: the single reconciliation authority.

Capability mirror of the reference's `ServeController`
(`serve/controller.py:61`) + `DeploymentStateManager`
(`serve/_private/deployment_state.py:958,1767`): holds target state, starts/
stops replica actors toward it, versions the routing table (long-poll
`serve/_private/long_poll.py` role: routers poll ``snapshot(version)``),
and applies the autoscaling policy on router-reported metrics
(`serve/_private/autoscaling_policy.py:93`).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional


class ServeController:
    def __init__(self):
        self._deployments: Dict[str, Dict[str, Any]] = {}
        self._version = 0
        self._replica_seq = 0
        # per-node HTTP proxies (reference: http_state.py HTTPProxyState
        # reconciliation); node_id -> {"actor", "address"}
        self._proxies: Dict[str, Dict[str, Any]] = {}
        self._proxy_http: Optional[dict] = None
        self._last_proxy_check = 0.0
        self._replica_nodes: Dict[str, str] = {}  # replica id -> node id

    # -- deploy / delete ----------------------------------------------------
    def deploy(self, name: str, callable_blob: bytes, init_args: tuple,
               init_kwargs: dict, config: dict,
               route_prefix: Optional[str]) -> bool:
        entry = self._deployments.get(name)
        if entry is None:
            entry = {"replicas": [], "metrics": {}, "last_scaled": 0.0}
            self._deployments[name] = entry
        entry.update(callable_blob=callable_blob, init_args=init_args,
                     init_kwargs=init_kwargs, config=dict(config),
                     route_prefix=route_prefix)
        # full restart on redeploy of code/config (simple + correct);
        # user_config-only updates go through reconfigure()
        self._scale_to(name, 0)
        self._reconcile(name)
        self._version += 1
        return True

    def reconfigure_deployment(self, name: str, user_config: Any) -> bool:
        entry = self._deployments[name]
        entry["config"]["user_config"] = user_config
        from .. import api
        api.get([m.reconfigure.remote(user_config)
                 for r in entry["replicas"]
                 for m in (r.get("gang") or [r["handle"]])], timeout=60.0)
        self._version += 1
        return True

    def delete(self, name: str) -> bool:
        if name in self._deployments:
            self._scale_to(name, 0)
            del self._deployments[name]
            self._version += 1
        return True

    def shutdown_all(self) -> bool:
        for name in list(self._deployments):
            self.delete(name)
        return True

    # -- reconciliation -----------------------------------------------------
    def _reconcile(self, name: str) -> None:
        entry = self._deployments[name]
        cfg = entry["config"]
        target = cfg["num_replicas"]
        auto = cfg.get("autoscaling_config")
        if auto:
            target = max(auto["min_replicas"],
                         min(target, auto["max_replicas"]))
            cfg["num_replicas"] = target
        self._scale_to(name, target)

    def _scale_to(self, name: str, target: int) -> None:
        from .. import api
        from .replica import ServeReplica
        entry = self._deployments[name]
        cfg = entry.get("config", {})
        gang_size = int(cfg.get("gang_size", 1) or 1)
        while len(entry["replicas"]) < target:
            self._replica_seq += 1
            rid = f"{name}#{self._replica_seq}"
            if gang_size > 1:
                # Multi-process replica: a placement-group gang hosting one
                # sharded program (serve/gang.py); the routing table carries
                # only the leader, so the router sees one unit.
                from .gang import start_gang_replica
                entry["replicas"].append(
                    start_gang_replica(name, rid, entry, cfg))
                continue
            opts = dict(cfg.get("ray_actor_options") or {})
            handle = api.remote(ServeReplica).options(
                max_concurrency=int(cfg.get("max_concurrent_queries", 8)),
                num_cpus=opts.get("num_cpus", 0.1),
                # detached: a replica must outlive the JOB that deployed
                # it (e.g. a `serve-deploy` CLI process) — Serve owns
                # replica lifecycle via scale-down/shutdown, the job GC
                # does not (reference: all serve actors are detached)
                lifetime="detached",
            ).remote(name, rid, entry["callable_blob"],
                     entry["init_args"], entry["init_kwargs"],
                     cfg.get("user_config"))
            entry["replicas"].append({"id": rid, "handle": handle})
        while len(entry["replicas"]) > target:
            rep = entry["replicas"].pop()
            self._replica_nodes.pop(rep["id"], None)
            self._audit_kill(name, rep["id"], target)
            if rep.get("gang"):
                from .gang import stop_gang_replica
                stop_gang_replica(rep)
                continue
            try:
                api.kill(rep["handle"])
            except Exception:
                pass
        self._version += 1

    @staticmethod
    def _audit_kill(name: str, replica_id: str, target: int) -> None:
        """Structured cluster event per replica teardown — when a
        request races a kill, the events API says who killed what."""
        why = (f"scale to {target}" if target >= 0
               else "found dead; replacing")
        try:
            from .. import state
            state.report_event(
                f"serve: removing replica {replica_id} of {name!r} "
                f"({why})", severity="INFO", source="serve")
        except Exception:
            pass

    # -- per-node HTTP proxies ---------------------------------------------
    def ensure_proxies(self, http: dict) -> Dict[str, str]:
        """Reconcile one HTTPProxy actor per alive node (reference:
        `serve/_private/http_state.py:28` proxy-state manager).  Each
        proxy binds an ephemeral port on its node and the table maps
        node_id -> http address; routers inside each proxy prefer
        same-node replicas, so ingress on any node serves local traffic
        without a cross-node hop when a local replica exists."""
        from .. import api, state
        from ..util.scheduling_strategies import \
            NodeAffinitySchedulingStrategy
        from .http_proxy import HTTPProxy
        self._proxy_http = dict(http)
        alive = {n["id"]: n for n in state.list_nodes() if n.get("alive")}
        # proxies whose ACTOR died while the node stayed alive must be
        # replaced too — check the actor table, not just node membership
        dead_aids = set()
        try:
            dead_aids = {row["actor_id"] for row in state.list_actors()
                         if row.get("state") == "DEAD"}
        except Exception:
            pass
        for nid in list(self._proxies):
            entry = self._proxies[nid]
            if nid in alive and \
                    entry["actor"]._actor_id not in dead_aids:
                continue
            self._proxies.pop(nid)
            try:
                api.kill(entry["actor"])
            except Exception:
                pass
        me = api.get_actor("serve::controller")
        for nid in alive:
            if nid in self._proxies:
                continue
            # Fire-and-forget: the proxy pushes its bound address via
            # register_proxy once live.  NEVER await it here — this
            # method runs inside the controller actor and the proxy's
            # first routing snapshot calls back into this same actor.
            actor = api.remote(HTTPProxy).options(
                num_cpus=0.05, max_concurrency=64, lifetime="detached",
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    node_id=nid, soft=False),
            ).remote(me, http.get("host", "127.0.0.1"), 0, nid)
            self._proxies[nid] = {"actor": actor, "address": None}
        return self.proxy_table()

    def register_proxy(self, node_id: str, address: str) -> bool:
        entry = self._proxies.get(node_id)
        if entry is not None:
            entry["address"] = address
        return True

    def adopt_proxy(self, node_id: str, actor: Any, address: str) -> bool:
        """Track a proxy created OUTSIDE the controller (the HeadOnly
        boot path) so proxy_statuses reports it and stop_proxies reaps
        it — detached actors have no job GC to fall back on."""
        self._proxies[node_id] = {"actor": actor, "address": address}
        return True

    def proxy_table(self) -> Dict[str, str]:
        """node_id -> address, for proxies that have announced."""
        return {nid: p["address"] for nid, p in self._proxies.items()
                if p["address"]}

    def stop_proxies(self) -> bool:
        from .. import api
        for p in self._proxies.values():
            try:
                api.kill(p["actor"])
            except Exception:
                pass
        self._proxies.clear()
        return True

    def _maybe_reconcile_proxies(self) -> None:
        """Piggybacked on router metric reports: pick up node joins and
        deaths within ~5 s without a dedicated loop."""
        if self._proxy_http is None:
            return
        now = time.monotonic()
        if now - self._last_proxy_check < 5.0:
            return
        self._last_proxy_check = now
        try:
            self.ensure_proxies(self._proxy_http)
        except Exception:
            pass  # transient state-API failure; next report retries

    def _maybe_heal_replicas(self) -> None:
        """Replace DEAD replica actors (reference: deployment_state's
        replica health checks — a replica whose worker died, was
        OOM-killed, or lost its node gets a fresh replacement toward
        the target count).  Throttled; piggybacks on metric reports."""
        now = time.monotonic()
        if now - getattr(self, "_last_heal_check", 0.0) < 5.0:
            return
        self._last_heal_check = now
        try:
            from .. import state
            dead = {row["actor_id"] for row in state.list_actors()
                    if row.get("state") == "DEAD"}
        except Exception:
            return
        if not dead:
            return
        for name, entry in self._deployments.items():
            alive = []
            lost = 0
            for rep in entry["replicas"]:
                handle = (rep.get("gang") or [rep["handle"]])[0]
                if handle._actor_id in dead:
                    lost += 1
                    self._replica_nodes.pop(rep["id"], None)
                    self._audit_kill(name, rep["id"], -1)
                    if rep.get("gang"):
                        from .gang import stop_gang_replica
                        try:
                            stop_gang_replica(rep)
                        except Exception:
                            pass
                else:
                    alive.append(rep)
            if lost:
                entry["replicas"] = alive
                self._reconcile(name)   # refill to the target count
                self._version += 1

    # -- routing state ------------------------------------------------------
    def _resolve_replica_nodes(self) -> None:
        """Fill the replica->node cache for locality routing with ONE
        actor-table RPC, at most once per second.  Only truthy node ids
        are cached: a replica still PENDING_CREATION has node_id None,
        and caching that would disable locality for its whole life."""
        unresolved = []
        for entry in self._deployments.values():
            for rep in entry["replicas"]:
                if not self._replica_nodes.get(rep["id"]):
                    unresolved.append(rep)
        if not unresolved:
            return
        now = time.monotonic()
        if now - getattr(self, "_last_node_resolve", 0.0) < 1.0:
            return
        self._last_node_resolve = now
        try:
            from .. import state
            by_aid = {row.get("actor_id"): row.get("node_id")
                      for row in state.list_actors()}
        except Exception:
            return  # transient; next snapshot retries
        newly = 0
        for rep in unresolved:
            handle = (rep.get("gang") or [rep["handle"]])[0]
            nid = by_aid.get(handle._actor_id)  # ids are bytes on the wire
            if nid:
                self._replica_nodes[rep["id"]] = nid
                newly += 1
        if newly:
            # routers that already saw this version must re-pull to get
            # the node annotations, or locality stays off until the next
            # unrelated table change
            self._version += 1

    def snapshot(self, known_version: int = -1) -> Optional[dict]:
        """Routing table if newer than known_version (long-poll pull)."""
        if known_version == self._version:
            return None
        self._resolve_replica_nodes()
        table = {}
        for name, entry in self._deployments.items():
            table[name] = {
                "route_prefix": entry.get("route_prefix"),
                "ingress": entry["config"].get("ingress", False),
                "max_concurrent_queries":
                    entry["config"].get("max_concurrent_queries", 8),
                "replicas": [{"id": r["id"], "handle": r["handle"],
                              "node_id":
                                  self._replica_nodes.get(r["id"])}
                             for r in entry["replicas"]],
            }
        return {"version": self._version, "table": table}

    def list_deployments(self) -> Dict[str, dict]:
        return {name: {"num_replicas": len(e["replicas"]),
                       "route_prefix": e.get("route_prefix"),
                       "config": {k: v for k, v in e["config"].items()
                                  if k != "ray_actor_options"}}
                for name, e in self._deployments.items()}

    # -- autoscaling --------------------------------------------------------
    def report_metrics(self, name: str, ongoing_per_replica: List[int]
                       ) -> bool:
        """Router-reported in-flight counts drive the basic autoscaler."""
        self._maybe_reconcile_proxies()
        self._maybe_heal_replicas()     # 5s-throttled internally
        self._resolve_replica_nodes()   # 1s-throttled internally
        entry = self._deployments.get(name)
        if entry is None:
            return False
        cfg = entry["config"]
        auto = cfg.get("autoscaling_config")
        if not auto:
            return True
        now = time.monotonic()
        n = max(len(ongoing_per_replica), 1)
        avg = sum(ongoing_per_replica) / n
        target_per = auto["target_num_ongoing_requests_per_replica"]
        desired = min(max(
            int(-(-sum(ongoing_per_replica) // target_per) or 1),
            auto["min_replicas"]), auto["max_replicas"])
        cur = len(entry["replicas"])
        delay = (auto["upscale_delay_s"] if desired > cur
                 else auto["downscale_delay_s"])
        if desired != cur and now - entry["last_scaled"] >= delay:
            entry["last_scaled"] = now
            cfg["num_replicas"] = desired
            self._scale_to(name, desired)
        return True
