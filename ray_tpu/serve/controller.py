"""Serve controller: the single reconciliation authority.

Capability mirror of the reference's `ServeController`
(`serve/controller.py:61`) + `DeploymentStateManager`
(`serve/_private/deployment_state.py:958,1767`): holds target state, starts/
stops replica actors toward it, versions the routing table (long-poll
`serve/_private/long_poll.py` role: routers poll ``snapshot(version)``),
and applies the autoscaling policy on router-reported metrics
(`serve/_private/autoscaling_policy.py:93`).
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional


def _process_core():
    """This process's CoreClient, creating it from the worker context
    when needed (a serve actor's __init__ may run before any api call
    lazily built one).  Never bootstraps a cluster."""
    from ..core.driver import get_global_core
    core = get_global_core()
    if core is None and os.environ.get("RAY_TPU_WORKER_CONTEXT"):
        from ..api import _ensure_initialized
        core = _ensure_initialized()
    return core


class ServeController:
    def __init__(self):
        self._deployments: Dict[str, Dict[str, Any]] = {}
        self._version = 0
        self._replica_seq = 0
        # per-node HTTP proxies (reference: http_state.py HTTPProxyState
        # reconciliation); node_id -> {"actor", "address"}
        self._proxies: Dict[str, Dict[str, Any]] = {}
        self._proxy_http: Optional[dict] = None
        self._last_proxy_check = 0.0
        self._replica_nodes: Dict[str, str] = {}  # replica id -> node id
        # drain evacuations in flight: doomed replica id -> {"name",
        # "replacement"} — the replacement is pre-started BEFORE the
        # draining replica stops, so capacity never dips
        self._evacuations: Dict[str, Dict[str, Any]] = {}
        # Node-membership push: a dead/draining node invalidates the
        # replica->node locality cache immediately.  A migrated replica
        # (same actor, new node) otherwise keeps its stale annotation
        # forever and every router evicts it as if it were still on the
        # corpse.
        try:
            core = _process_core()
            if core is not None:
                core.subscribe_node_events(self._on_node_event)
        except Exception:
            pass

    def _on_node_event(self, data: Dict[str, Any]) -> None:
        """A node DIED: drop its replicas' locality annotations so
        routers stop evicting replicas that are mid-restart elsewhere.
        DRAINING keeps the annotations — that eviction is the point."""
        if data.get("event") != "dead":
            return
        nid = data.get("node_id")
        if not nid:
            return
        stale = [rid for rid, n in self._replica_nodes.items() if n == nid]
        for rid in stale:
            self._replica_nodes.pop(rid, None)
        if stale:
            # force routers to re-pull: the fresh table drops the stale
            # annotations and _resolve_replica_nodes re-resolves them
            self._version += 1

    # -- deploy / delete ----------------------------------------------------
    def deploy(self, name: str, callable_blob: bytes, init_args: tuple,
               init_kwargs: dict, config: dict,
               route_prefix: Optional[str]) -> bool:
        entry = self._deployments.get(name)
        if entry is None:
            entry = {"replicas": [], "metrics": {}, "last_scaled": 0.0}
            self._deployments[name] = entry
        entry.update(callable_blob=callable_blob, init_args=init_args,
                     init_kwargs=init_kwargs, config=dict(config),
                     route_prefix=route_prefix)
        # full restart on redeploy of code/config (simple + correct);
        # user_config-only updates go through reconfigure()
        self._scale_to(name, 0)
        self._reconcile(name)
        self._version += 1
        return True

    def reconfigure_deployment(self, name: str, user_config: Any) -> bool:
        entry = self._deployments[name]
        entry["config"]["user_config"] = user_config
        from .. import api
        api.get([m.reconfigure.remote(user_config)
                 for r in entry["replicas"]
                 for m in (r.get("gang") or [r["handle"]])], timeout=60.0)
        self._version += 1
        return True

    def delete(self, name: str) -> bool:
        if name in self._deployments:
            self._scale_to(name, 0)
            del self._deployments[name]
            self._version += 1
        return True

    def shutdown_all(self) -> bool:
        for name in list(self._deployments):
            self.delete(name)
        return True

    # -- reconciliation -----------------------------------------------------
    def _reconcile(self, name: str) -> None:
        entry = self._deployments[name]
        cfg = entry["config"]
        target = cfg["num_replicas"]
        auto = cfg.get("autoscaling_config")
        if auto:
            target = max(auto["min_replicas"],
                         min(target, auto["max_replicas"]))
            cfg["num_replicas"] = target
        self._scale_to(name, target)

    def _start_replica(self, name: str, entry: Dict[str, Any]
                       ) -> Dict[str, Any]:
        """Start one replica (or gang replica) and append it to the
        deployment's table; returns the new table row."""
        from .. import api
        from .replica import ServeReplica
        cfg = entry.get("config", {})
        gang_size = int(cfg.get("gang_size", 1) or 1)
        self._replica_seq += 1
        rid = f"{name}#{self._replica_seq}"
        if gang_size > 1:
            # Multi-process replica: a placement-group gang hosting one
            # sharded program (serve/gang.py); the routing table carries
            # only the leader, so the router sees one unit.
            from .gang import start_gang_replica
            rep = start_gang_replica(name, rid, entry, cfg)
            entry["replicas"].append(rep)
            return rep
        opts = dict(cfg.get("ray_actor_options") or {})
        handle = api.remote(ServeReplica).options(
            max_concurrency=int(cfg.get("max_concurrent_queries", 8)),
            num_cpus=opts.get("num_cpus", 0.1),
            # detached: a replica must outlive the JOB that deployed
            # it (e.g. a `serve-deploy` CLI process) — Serve owns
            # replica lifecycle via scale-down/shutdown, the job GC
            # does not (reference: all serve actors are detached)
            lifetime="detached",
        ).remote(name, rid, entry["callable_blob"],
                 entry["init_args"], entry["init_kwargs"],
                 cfg.get("user_config"))
        rep = {"id": rid, "handle": handle}
        entry["replicas"].append(rep)
        return rep

    def _scale_to(self, name: str, target: int) -> None:
        from .. import api
        entry = self._deployments[name]
        while len(entry["replicas"]) < target:
            self._start_replica(name, entry)
        while len(entry["replicas"]) > target:
            rep = entry["replicas"].pop()
            self._replica_nodes.pop(rep["id"], None)
            self._audit_kill(name, rep["id"], target)
            if rep.get("gang"):
                from .gang import stop_gang_replica
                stop_gang_replica(rep)
                continue
            try:
                api.kill(rep["handle"])
            except Exception:
                pass
        self._version += 1

    @staticmethod
    def _audit_kill(name: str, replica_id: str, target: int) -> None:
        """Structured cluster event per replica teardown — when a
        request races a kill, the events API says who killed what."""
        why = (f"scale to {target}" if target >= 0
               else "node draining; replacement pre-started"
               if target == -2 else "found dead; replacing")
        try:
            from .. import state
            state.report_event(
                f"serve: removing replica {replica_id} of {name!r} "
                f"({why})", severity="INFO", source="serve")
        except Exception:
            pass

    # -- per-node HTTP proxies ---------------------------------------------
    def ensure_proxies(self, http: dict) -> Dict[str, str]:
        """Reconcile one HTTPProxy actor per alive node (reference:
        `serve/_private/http_state.py:28` proxy-state manager).  Each
        proxy binds an ephemeral port on its node and the table maps
        node_id -> http address; routers inside each proxy prefer
        same-node replicas, so ingress on any node serves local traffic
        without a cross-node hop when a local replica exists."""
        from .. import api, state
        from ..util.scheduling_strategies import \
            NodeAffinitySchedulingStrategy
        from .http_proxy import HTTPProxy
        self._proxy_http = dict(http)
        alive = {n["id"]: n for n in state.list_nodes() if n.get("alive")}
        # proxies whose ACTOR died while the node stayed alive must be
        # replaced too — check the actor table, not just node membership
        dead_aids = set()
        try:
            dead_aids = {row["actor_id"] for row in state.list_actors()
                         if row.get("state") == "DEAD"}
        except Exception:
            pass
        for nid in list(self._proxies):
            entry = self._proxies[nid]
            if nid in alive and \
                    entry["actor"]._actor_id not in dead_aids:
                continue
            self._proxies.pop(nid)
            try:
                api.kill(entry["actor"])
            except Exception:
                pass
        me = api.get_actor("serve::controller")
        for nid in alive:
            if nid in self._proxies:
                continue
            # Fire-and-forget: the proxy pushes its bound address via
            # register_proxy once live.  NEVER await it here — this
            # method runs inside the controller actor and the proxy's
            # first routing snapshot calls back into this same actor.
            actor = api.remote(HTTPProxy).options(
                num_cpus=0.05, max_concurrency=64, lifetime="detached",
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    node_id=nid, soft=False),
            ).remote(me, http.get("host", "127.0.0.1"), 0, nid)
            self._proxies[nid] = {"actor": actor, "address": None}
        return self.proxy_table()

    def register_proxy(self, node_id: str, address: str) -> bool:
        entry = self._proxies.get(node_id)
        if entry is not None:
            entry["address"] = address
        return True

    def adopt_proxy(self, node_id: str, actor: Any, address: str) -> bool:
        """Track a proxy created OUTSIDE the controller (the HeadOnly
        boot path) so proxy_statuses reports it and stop_proxies reaps
        it — detached actors have no job GC to fall back on."""
        self._proxies[node_id] = {"actor": actor, "address": address}
        return True

    def proxy_table(self) -> Dict[str, str]:
        """node_id -> address, for proxies that have announced."""
        return {nid: p["address"] for nid, p in self._proxies.items()
                if p["address"]}

    def stop_proxies(self) -> bool:
        from .. import api
        for p in self._proxies.values():
            try:
                api.kill(p["actor"])
            except Exception:
                pass
        self._proxies.clear()
        return True

    def _maybe_reconcile_proxies(self) -> None:
        """Piggybacked on router metric reports: pick up node joins and
        deaths within ~5 s without a dedicated loop."""
        if self._proxy_http is None:
            return
        now = time.monotonic()
        if now - self._last_proxy_check < 5.0:
            return
        self._last_proxy_check = now
        try:
            self.ensure_proxies(self._proxy_http)
        except Exception:
            pass  # transient state-API failure; next report retries

    def _maybe_heal_replicas(self) -> None:
        """Replace DEAD replica actors (reference: deployment_state's
        replica health checks — a replica whose worker died, was
        OOM-killed, or lost its node gets a fresh replacement toward
        the target count).  Throttled; piggybacks on metric reports."""
        now = time.monotonic()
        if now - getattr(self, "_last_heal_check", 0.0) < 5.0:
            return
        self._last_heal_check = now
        try:
            from .. import state
            dead = {row["actor_id"] for row in state.list_actors()
                    if row.get("state") == "DEAD"}
        except Exception:
            return
        if not dead:
            return
        for name, entry in self._deployments.items():
            alive = []
            lost = 0
            for rep in entry["replicas"]:
                handle = (rep.get("gang") or [rep["handle"]])[0]
                if handle._actor_id in dead:
                    lost += 1
                    self._replica_nodes.pop(rep["id"], None)
                    self._audit_kill(name, rep["id"], -1)
                    if rep.get("gang"):
                        from .gang import stop_gang_replica
                        try:
                            stop_gang_replica(rep)
                        except Exception:
                            pass
                else:
                    alive.append(rep)
            if lost:
                entry["replicas"] = alive
                self._reconcile(name)   # refill to the target count
                self._version += 1

    def _maybe_evacuate_draining(self) -> None:
        """Zero-downtime replica evacuation off DRAINING nodes
        (reference rationale: deployment_state's graceful scale — here
        triggered by the cluster's drain protocol).  Two-phase, spread
        over poll ticks: (1) pre-start a replacement for every ALIVE
        replica sitting on a draining node, (2) once the replacement is
        ALIVE on a live node, stop the doomed replica.  Also refreshes
        the locality cache for replicas the core controller already
        migrated (same actor, new node) so routers stop evicting them.
        Throttled; piggybacks on router metric reports."""
        now = time.monotonic()
        if now - getattr(self, "_last_drain_check", 0.0) < 2.0:
            return
        self._last_drain_check = now
        try:
            from .. import state
            nodes = state.list_nodes()
        except Exception:
            return  # transient state-API failure; next tick retries
        alive_ids, draining = set(), set()
        for n in nodes:
            if n.get("alive"):
                alive_ids.add(n["id"])
                if n.get("draining"):
                    draining.add(n["id"])
        # cached annotations naming departed nodes must be re-resolved —
        # a drained node's replicas restarted elsewhere, and routers
        # would keep evicting them on the corpse annotation
        stale = any(nid not in alive_ids
                    for nid in self._replica_nodes.values())
        if not draining and not self._evacuations and not stale:
            return
        try:
            from .. import state
            by_aid = {row.get("actor_id"): row
                      for row in state.list_actors()}
        except Exception:
            return
        replacing = {e["replacement"] for e in self._evacuations.values()}
        for name, entry in self._deployments.items():
            for rep in list(entry["replicas"]):
                rid = rep["id"]
                handle = (rep.get("gang") or [rep["handle"]])[0]
                row = by_aid.get(handle._actor_id) or {}
                nid = row.get("node_id")
                cached = self._replica_nodes.get(rid)
                if nid and cached != nid:
                    # migrated replica: refresh the node annotation or
                    # routers keep treating it as draining forever
                    self._replica_nodes[rid] = nid
                    self._version += 1
                elif not nid and cached and cached not in alive_ids:
                    # mid-restart off a gone node: drop the corpse
                    # annotation so routers may route to it again once
                    # the restart lands
                    self._replica_nodes.pop(rid, None)
                    self._version += 1
                if rid in self._evacuations or rid in replacing:
                    continue
                if nid in draining and row.get("state") == "ALIVE":
                    replacement = self._start_replica(name, entry)
                    # keep the doomed replica LAST so a concurrent
                    # scale-down pops it, never the replacement
                    entry["replicas"].remove(rep)
                    entry["replicas"].append(rep)
                    self._evacuations[rid] = {
                        "name": name, "replacement": replacement["id"]}
                    self._version += 1
        # phase 2: replacements that came up take over; doomed replicas stop
        for rid, info in list(self._evacuations.items()):
            entry = self._deployments.get(info["name"])
            rep = None if entry is None else next(
                (r for r in entry["replicas"] if r["id"] == rid), None)
            new_rep = None if entry is None else next(
                (r for r in entry["replicas"]
                 if r["id"] == info["replacement"]), None)
            if rep is None or new_rep is None:
                self._evacuations.pop(rid, None)
                continue  # deleted/healed under us; reconcile covers it
            nh = (new_rep.get("gang") or [new_rep["handle"]])[0]
            row = by_aid.get(nh._actor_id) or {}
            if row.get("state") != "ALIVE" or row.get("node_id") in draining:
                continue  # replacement not ready yet; next tick
            from .. import api
            if not rep.get("gang"):
                # Migrate live decode sessions off the doomed replica
                # BEFORE stopping it: flip its engines into drain mode
                # (new starts shed with the typed 503; streams hand off
                # via the ``migrating`` reply and the proxy's failover
                # client re-admits them on the replacement), then wait
                # — bounded — for the live-session count to reach zero
                # so a drain with active streams drops none of them.
                from ..core.config import GlobalConfig
                if "session_deadline" not in info:
                    info["session_deadline"] = now + \
                        GlobalConfig.serve_session_migration_timeout_s
                    try:
                        api.get(rep["handle"].prepare_drain.remote(),
                                timeout=10.0)
                    except Exception:
                        pass  # dead/hung replica: the deadline covers it
                live = 0
                try:
                    live = api.get(rep["handle"].drain_status.remote(),
                                   timeout=5.0).get("live_sessions", 0)
                except Exception:
                    live = 0
                if live > 0 and now < info["session_deadline"]:
                    continue   # sessions still handing off; next tick
            entry["replicas"].remove(rep)
            self._replica_nodes.pop(rid, None)
            self._audit_kill(info["name"], rid, -2)
            if rep.get("gang"):
                from .gang import stop_gang_replica
                try:
                    stop_gang_replica(rep)
                except Exception:
                    pass
            else:
                try:
                    api.kill(rep["handle"])
                except Exception:
                    pass
            self._evacuations.pop(rid, None)
            self._version += 1

    # -- routing state ------------------------------------------------------
    def _resolve_replica_nodes(self) -> None:
        """Fill the replica->node cache for locality routing with ONE
        actor-table RPC, at most once per second.  Only truthy node ids
        are cached: a replica still PENDING_CREATION has node_id None,
        and caching that would disable locality for its whole life."""
        unresolved = []
        for entry in self._deployments.values():
            for rep in entry["replicas"]:
                if not self._replica_nodes.get(rep["id"]):
                    unresolved.append(rep)
        if not unresolved:
            return
        now = time.monotonic()
        if now - getattr(self, "_last_node_resolve", 0.0) < 1.0:
            return
        self._last_node_resolve = now
        try:
            from .. import state
            by_aid = {row.get("actor_id"): row.get("node_id")
                      for row in state.list_actors()}
        except Exception:
            return  # transient; next snapshot retries
        newly = 0
        for rep in unresolved:
            handle = (rep.get("gang") or [rep["handle"]])[0]
            nid = by_aid.get(handle._actor_id)  # ids are bytes on the wire
            if nid:
                self._replica_nodes[rep["id"]] = nid
                newly += 1
        if newly:
            # routers that already saw this version must re-pull to get
            # the node annotations, or locality stays off until the next
            # unrelated table change
            self._version += 1

    def snapshot(self, known_version: int = -1) -> Optional[dict]:
        """Routing table if newer than known_version (long-poll pull)."""
        # Reconcile drains on the POLL path too (throttled): when every
        # replica of a deployment is evicted, completions — and with
        # them report_metrics — stop entirely, but failing routers keep
        # polling snapshot; without this hook the stale annotations
        # would never refresh and the outage would be permanent.
        self._maybe_evacuate_draining()
        if known_version == self._version:
            return None
        self._resolve_replica_nodes()
        table = {}
        for name, entry in self._deployments.items():
            table[name] = {
                "route_prefix": entry.get("route_prefix"),
                "ingress": entry["config"].get("ingress", False),
                "max_concurrent_queries":
                    entry["config"].get("max_concurrent_queries", 8),
                "replicas": [{"id": r["id"], "handle": r["handle"],
                              "node_id":
                                  self._replica_nodes.get(r["id"])}
                             for r in entry["replicas"]],
            }
        return {"version": self._version, "table": table}

    def list_deployments(self) -> Dict[str, dict]:
        return {name: {"num_replicas": len(e["replicas"]),
                       "route_prefix": e.get("route_prefix"),
                       "config": {k: v for k, v in e["config"].items()
                                  if k != "ray_actor_options"}}
                for name, e in self._deployments.items()}

    # -- autoscaling --------------------------------------------------------
    def report_metrics(self, name: str, ongoing_per_replica: List[int]
                       ) -> bool:
        """Router-reported in-flight counts drive the basic autoscaler."""
        self._maybe_reconcile_proxies()
        self._maybe_heal_replicas()     # 5s-throttled internally
        self._maybe_evacuate_draining()  # 2s-throttled internally
        self._resolve_replica_nodes()   # 1s-throttled internally
        entry = self._deployments.get(name)
        if entry is None:
            return False
        cfg = entry["config"]
        auto = cfg.get("autoscaling_config")
        if not auto:
            return True
        now = time.monotonic()
        n = max(len(ongoing_per_replica), 1)
        avg = sum(ongoing_per_replica) / n
        target_per = auto["target_num_ongoing_requests_per_replica"]
        desired = min(max(
            int(-(-sum(ongoing_per_replica) // target_per) or 1),
            auto["min_replicas"]), auto["max_replicas"])
        cur = len(entry["replicas"])
        delay = (auto["upscale_delay_s"] if desired > cur
                 else auto["downscale_delay_s"])
        if desired != cur and now - entry["last_scaled"] >= delay:
            entry["last_scaled"] = now
            cfg["num_replicas"] = desired
            self._scale_to(name, desired)
        return True
