"""Serve controller: the single reconciliation authority.

Capability mirror of the reference's `ServeController`
(`serve/controller.py:61`) + `DeploymentStateManager`
(`serve/_private/deployment_state.py:958,1767`): holds target state, starts/
stops replica actors toward it, versions the routing table (long-poll
`serve/_private/long_poll.py` role: routers poll ``snapshot(version)``),
and applies the autoscaling policy on router-reported metrics
(`serve/_private/autoscaling_policy.py:93`).
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional


def _process_core():
    """This process's CoreClient, creating it from the worker context
    when needed (a serve actor's __init__ may run before any api call
    lazily built one).  Never bootstraps a cluster."""
    from ..core.driver import get_global_core
    core = get_global_core()
    if core is None and os.environ.get("RAY_TPU_WORKER_CONTEXT"):
        from ..api import _ensure_initialized
        core = _ensure_initialized()
    return core


class ServeController:
    def __init__(self):
        self._deployments: Dict[str, Dict[str, Any]] = {}
        self._version = 0
        self._replica_seq = 0
        # per-node HTTP proxies (reference: http_state.py HTTPProxyState
        # reconciliation); node_id -> {"actor", "address"}
        self._proxies: Dict[str, Dict[str, Any]] = {}
        self._proxy_http: Optional[dict] = None
        self._last_proxy_check = 0.0
        self._replica_nodes: Dict[str, str] = {}  # replica id -> node id
        # drain evacuations in flight: doomed replica id -> {"name",
        # "replacement"} — the replacement is pre-started BEFORE the
        # draining replica stops, so capacity never dips
        self._evacuations: Dict[str, Dict[str, Any]] = {}
        # autoscale scale-downs in flight: replica id -> {"name",
        # "deadline"} — the victim drains (engine sheds new starts,
        # live sessions migrate via the failover client) and is only
        # killed at live_sessions == 0 or the migration deadline, so a
        # scale-down never drops a stream
        self._retiring: Dict[str, Dict[str, Any]] = {}
        # SUSPECT (gray) nodes from the pubsub push: their replicas'
        # capacity is down-weighted by the autoscale policy, growing
        # the fleet around a brownout before it shows up as errors
        self._suspect_nodes: set = set()
        # replica boot-time EWMA (start -> ALIVE in the actor table):
        # the Retry-After on scale-up-in-progress sheds, so clients
        # re-arrive right as the new capacity lands
        self._boot_pending: Dict[str, float] = {}
        self._boot_ewma: Optional[float] = None
        self._last_autoscale = 0.0
        # Node-membership push: a dead/draining node invalidates the
        # replica->node locality cache immediately.  A migrated replica
        # (same actor, new node) otherwise keeps its stale annotation
        # forever and every router evicts it as if it were still on the
        # corpse.
        try:
            core = _process_core()
            if core is not None:
                core.subscribe_node_events(self._on_node_event)
        except Exception:
            pass

    def _on_node_event(self, data: Dict[str, Any]) -> None:
        """A node DIED: drop its replicas' locality annotations so
        routers stop evicting replicas that are mid-restart elsewhere.
        DRAINING keeps the annotations — that eviction is the point.
        SUSPECT membership feeds the autoscale policy's capacity
        down-weighting (routers route around those nodes on their own
        copy of the same events)."""
        ev = data.get("event")
        nid = data.get("node_id") or (data.get("node") or {}).get("id")
        if ev == "suspect" and nid:
            self._suspect_nodes.add(nid)
            return
        if ev in ("rejoined", "added") and nid:
            self._suspect_nodes.discard(nid)
            return
        if ev != "dead":
            return
        if not nid:
            return
        self._suspect_nodes.discard(nid)
        stale = [rid for rid, n in self._replica_nodes.items() if n == nid]
        for rid in stale:
            self._replica_nodes.pop(rid, None)
        if stale:
            # force routers to re-pull: the fresh table drops the stale
            # annotations and _resolve_replica_nodes re-resolves them
            self._version += 1

    # -- deploy / delete ----------------------------------------------------
    def deploy(self, name: str, callable_blob: bytes, init_args: tuple,
               init_kwargs: dict, config: dict,
               route_prefix: Optional[str]) -> bool:
        entry = self._deployments.get(name)
        if entry is None:
            entry = {"replicas": [], "metrics": {}, "last_scaled": 0.0}
            self._deployments[name] = entry
        entry.update(callable_blob=callable_blob, init_args=init_args,
                     init_kwargs=init_kwargs, config=dict(config),
                     route_prefix=route_prefix)
        # full restart on redeploy of code/config (simple + correct);
        # user_config-only updates go through reconfigure()
        self._scale_to(name, 0)
        self._reconcile(name)
        self._version += 1
        return True

    def reconfigure_deployment(self, name: str, user_config: Any) -> bool:
        entry = self._deployments[name]
        entry["config"]["user_config"] = user_config
        from .. import api
        api.get([m.reconfigure.remote(user_config)
                 for r in entry["replicas"]
                 for m in (r.get("gang") or [r["handle"]])], timeout=60.0)
        self._version += 1
        return True

    def delete(self, name: str) -> bool:
        if name in self._deployments:
            self._scale_to(name, 0)
            del self._deployments[name]
            self._version += 1
        return True

    def shutdown_all(self) -> bool:
        for name in list(self._deployments):
            self.delete(name)
        return True

    # -- reconciliation -----------------------------------------------------
    def _reconcile(self, name: str) -> None:
        entry = self._deployments[name]
        cfg = entry["config"]
        target = cfg["num_replicas"]
        auto = cfg.get("autoscaling_config")
        if auto:
            target = max(auto["min_replicas"],
                         min(target, auto["max_replicas"]))
            cfg["num_replicas"] = target
        self._scale_to(name, target)

    def _start_replica(self, name: str, entry: Dict[str, Any]
                       ) -> Dict[str, Any]:
        """Start one replica (or gang replica) and append it to the
        deployment's table; returns the new table row."""
        from .. import api
        from .replica import ServeReplica
        cfg = entry.get("config", {})
        gang_size = int(cfg.get("gang_size", 1) or 1)
        self._replica_seq += 1
        rid = f"{name}#{self._replica_seq}"
        if gang_size > 1:
            # Multi-process replica: a placement-group gang hosting one
            # sharded program (serve/gang.py); the routing table carries
            # only the leader, so the router sees one unit.
            from .gang import start_gang_replica
            rep = start_gang_replica(name, rid, entry, cfg)
            entry["replicas"].append(rep)
            return rep
        opts = dict(cfg.get("ray_actor_options") or {})
        handle = api.remote(ServeReplica).options(
            max_concurrency=int(cfg.get("max_concurrent_queries", 8)),
            num_cpus=opts.get("num_cpus", 0.1),
            # detached: a replica must outlive the JOB that deployed
            # it (e.g. a `serve-deploy` CLI process) — Serve owns
            # replica lifecycle via scale-down/shutdown, the job GC
            # does not (reference: all serve actors are detached)
            lifetime="detached",
        ).remote(name, rid, entry["callable_blob"],
                 entry["init_args"], entry["init_kwargs"],
                 cfg.get("user_config"))
        rep = {"id": rid, "handle": handle}
        entry["replicas"].append(rep)
        self._boot_pending[rid] = time.monotonic()
        return rep

    def _scale_to(self, name: str, target: int) -> None:
        from .. import api
        entry = self._deployments[name]
        while len(entry["replicas"]) < target:
            self._start_replica(name, entry)
        while len(entry["replicas"]) > target:
            rep = entry["replicas"].pop()
            self._replica_nodes.pop(rep["id"], None)
            self._boot_pending.pop(rep["id"], None)
            self._retiring.pop(rep["id"], None)
            self._audit_kill(name, rep["id"], target)
            if rep.get("gang"):
                from .gang import stop_gang_replica
                stop_gang_replica(rep)
                continue
            try:
                api.kill(rep["handle"])
            except Exception:
                pass
        self._version += 1

    @staticmethod
    def _audit_kill(name: str, replica_id: str, target: int) -> None:
        """Structured cluster event per replica teardown — when a
        request races a kill, the events API says who killed what."""
        why = (f"scale to {target}" if target >= 0
               else "node draining; replacement pre-started"
               if target == -2
               else "autoscale down; sessions migrated first"
               if target == -3 else "found dead; replacing")
        try:
            from .. import state
            state.report_event(
                f"serve: removing replica {replica_id} of {name!r} "
                f"({why})", severity="INFO", source="serve")
        except Exception:
            pass

    # -- per-node HTTP proxies ---------------------------------------------
    def ensure_proxies(self, http: dict) -> Dict[str, str]:
        """Reconcile one HTTPProxy actor per alive node (reference:
        `serve/_private/http_state.py:28` proxy-state manager).  Each
        proxy binds an ephemeral port on its node and the table maps
        node_id -> http address; routers inside each proxy prefer
        same-node replicas, so ingress on any node serves local traffic
        without a cross-node hop when a local replica exists."""
        from .. import api, state
        from ..util.scheduling_strategies import \
            NodeAffinitySchedulingStrategy
        from .http_proxy import HTTPProxy
        self._proxy_http = dict(http)
        alive = {n["id"]: n for n in state.list_nodes() if n.get("alive")}
        # proxies whose ACTOR died while the node stayed alive must be
        # replaced too — check the actor table, not just node membership
        dead_aids = set()
        try:
            dead_aids = {row["actor_id"] for row in state.list_actors()
                         if row.get("state") == "DEAD"}
        except Exception:
            pass
        for nid in list(self._proxies):
            entry = self._proxies[nid]
            if nid in alive and \
                    entry["actor"]._actor_id not in dead_aids:
                continue
            self._proxies.pop(nid)
            try:
                api.kill(entry["actor"])
            except Exception:
                pass
        me = api.get_actor("serve::controller")
        for nid in alive:
            if nid in self._proxies:
                continue
            # Fire-and-forget: the proxy pushes its bound address via
            # register_proxy once live.  NEVER await it here — this
            # method runs inside the controller actor and the proxy's
            # first routing snapshot calls back into this same actor.
            actor = api.remote(HTTPProxy).options(
                num_cpus=0.05, max_concurrency=64, lifetime="detached",
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    node_id=nid, soft=False),
            ).remote(me, http.get("host", "127.0.0.1"), 0, nid)
            self._proxies[nid] = {"actor": actor, "address": None}
        return self.proxy_table()

    def register_proxy(self, node_id: str, address: str) -> bool:
        entry = self._proxies.get(node_id)
        if entry is not None:
            entry["address"] = address
        return True

    def adopt_proxy(self, node_id: str, actor: Any, address: str) -> bool:
        """Track a proxy created OUTSIDE the controller (the HeadOnly
        boot path) so proxy_statuses reports it and stop_proxies reaps
        it — detached actors have no job GC to fall back on."""
        self._proxies[node_id] = {"actor": actor, "address": address}
        return True

    def proxy_table(self) -> Dict[str, str]:
        """node_id -> address, for proxies that have announced."""
        return {nid: p["address"] for nid, p in self._proxies.items()
                if p["address"]}

    def stop_proxies(self) -> bool:
        from .. import api
        for p in self._proxies.values():
            try:
                api.kill(p["actor"])
            except Exception:
                pass
        self._proxies.clear()
        return True

    def _maybe_reconcile_proxies(self) -> None:
        """Piggybacked on router metric reports: pick up node joins and
        deaths within ~5 s without a dedicated loop."""
        if self._proxy_http is None:
            return
        now = time.monotonic()
        if now - self._last_proxy_check < 5.0:
            return
        self._last_proxy_check = now
        try:
            self.ensure_proxies(self._proxy_http)
        except Exception:
            pass  # transient state-API failure; next report retries

    def _maybe_heal_replicas(self) -> None:
        """Replace DEAD replica actors (reference: deployment_state's
        replica health checks — a replica whose worker died, was
        OOM-killed, or lost its node gets a fresh replacement toward
        the target count).  Throttled; piggybacks on metric reports."""
        now = time.monotonic()
        if now - getattr(self, "_last_heal_check", 0.0) < 5.0:
            return
        self._last_heal_check = now
        try:
            from .. import state
            dead = {row["actor_id"] for row in state.list_actors()
                    if row.get("state") == "DEAD"}
        except Exception:
            return
        if not dead:
            return
        for name, entry in self._deployments.items():
            alive = []
            lost = 0
            for rep in entry["replicas"]:
                handle = (rep.get("gang") or [rep["handle"]])[0]
                if handle._actor_id in dead:
                    lost += 1
                    self._replica_nodes.pop(rep["id"], None)
                    self._audit_kill(name, rep["id"], -1)
                    if rep.get("gang"):
                        from .gang import stop_gang_replica
                        try:
                            stop_gang_replica(rep)
                        except Exception:
                            pass
                else:
                    alive.append(rep)
            if lost:
                entry["replicas"] = alive
                self._reconcile(name)   # refill to the target count
                self._version += 1

    def _maybe_evacuate_draining(self) -> None:
        """Zero-downtime replica evacuation off DRAINING nodes
        (reference rationale: deployment_state's graceful scale — here
        triggered by the cluster's drain protocol).  Two-phase, spread
        over poll ticks: (1) pre-start a replacement for every ALIVE
        replica sitting on a draining node, (2) once the replacement is
        ALIVE on a live node, stop the doomed replica.  Also refreshes
        the locality cache for replicas the core controller already
        migrated (same actor, new node) so routers stop evicting them.
        Throttled; piggybacks on router metric reports."""
        now = time.monotonic()
        if now - getattr(self, "_last_drain_check", 0.0) < 2.0:
            return
        self._last_drain_check = now
        try:
            from .. import state
            nodes = state.list_nodes()
        except Exception:
            return  # transient state-API failure; next tick retries
        alive_ids, draining = set(), set()
        for n in nodes:
            if n.get("alive"):
                alive_ids.add(n["id"])
                if n.get("draining"):
                    draining.add(n["id"])
        # cached annotations naming departed nodes must be re-resolved —
        # a drained node's replicas restarted elsewhere, and routers
        # would keep evicting them on the corpse annotation
        stale = any(nid not in alive_ids
                    for nid in self._replica_nodes.values())
        if not draining and not self._evacuations and not stale:
            return
        try:
            from .. import state
            by_aid = {row.get("actor_id"): row
                      for row in state.list_actors()}
        except Exception:
            return
        replacing = {e["replacement"] for e in self._evacuations.values()}
        for name, entry in self._deployments.items():
            for rep in list(entry["replicas"]):
                rid = rep["id"]
                handle = (rep.get("gang") or [rep["handle"]])[0]
                row = by_aid.get(handle._actor_id) or {}
                nid = row.get("node_id")
                cached = self._replica_nodes.get(rid)
                if nid and cached != nid:
                    # migrated replica: refresh the node annotation or
                    # routers keep treating it as draining forever
                    self._replica_nodes[rid] = nid
                    self._version += 1
                elif not nid and cached and cached not in alive_ids:
                    # mid-restart off a gone node: drop the corpse
                    # annotation so routers may route to it again once
                    # the restart lands
                    self._replica_nodes.pop(rid, None)
                    self._version += 1
                if rid in self._evacuations or rid in replacing:
                    continue
                if nid in draining and row.get("state") == "ALIVE":
                    replacement = self._start_replica(name, entry)
                    # keep the doomed replica LAST so a concurrent
                    # scale-down pops it, never the replacement
                    entry["replicas"].remove(rep)
                    entry["replicas"].append(rep)
                    self._evacuations[rid] = {
                        "name": name, "replacement": replacement["id"]}
                    self._version += 1
        # phase 2: replacements that came up take over; doomed replicas stop
        for rid, info in list(self._evacuations.items()):
            entry = self._deployments.get(info["name"])
            rep = None if entry is None else next(
                (r for r in entry["replicas"] if r["id"] == rid), None)
            new_rep = None if entry is None else next(
                (r for r in entry["replicas"]
                 if r["id"] == info["replacement"]), None)
            if rep is None or new_rep is None:
                self._evacuations.pop(rid, None)
                continue  # deleted/healed under us; reconcile covers it
            nh = (new_rep.get("gang") or [new_rep["handle"]])[0]
            row = by_aid.get(nh._actor_id) or {}
            if row.get("state") != "ALIVE" or row.get("node_id") in draining:
                continue  # replacement not ready yet; next tick
            from .. import api
            if not rep.get("gang"):
                # Migrate live decode sessions off the doomed replica
                # BEFORE stopping it: flip its engines into drain mode
                # (new starts shed with the typed 503; streams hand off
                # via the ``migrating`` reply and the proxy's failover
                # client re-admits them on the replacement), then wait
                # — bounded — for the live-session count to reach zero
                # so a drain with active streams drops none of them.
                from ..core.config import GlobalConfig
                if "session_deadline" not in info:
                    info["session_deadline"] = now + \
                        GlobalConfig.serve_session_migration_timeout_s
                    try:
                        api.get(rep["handle"].prepare_drain.remote(),
                                timeout=10.0)
                    except Exception:
                        pass  # dead/hung replica: the deadline covers it
                live = 0
                try:
                    live = api.get(rep["handle"].drain_status.remote(),
                                   timeout=5.0).get("live_sessions", 0)
                except Exception:
                    live = 0
                if live > 0 and now < info["session_deadline"]:
                    continue   # sessions still handing off; next tick
            entry["replicas"].remove(rep)
            self._replica_nodes.pop(rid, None)
            self._audit_kill(info["name"], rid, -2)
            if rep.get("gang"):
                from .gang import stop_gang_replica
                try:
                    stop_gang_replica(rep)
                except Exception:
                    pass
            else:
                try:
                    api.kill(rep["handle"])
                except Exception:
                    pass
            self._evacuations.pop(rid, None)
            self._version += 1

    # -- routing state ------------------------------------------------------
    def _resolve_replica_nodes(self) -> None:
        """Fill the replica->node cache for locality routing with ONE
        actor-table RPC, at most once per second.  Only truthy node ids
        are cached: a replica still PENDING_CREATION has node_id None,
        and caching that would disable locality for its whole life."""
        unresolved = []
        for entry in self._deployments.values():
            for rep in entry["replicas"]:
                if not self._replica_nodes.get(rep["id"]):
                    unresolved.append(rep)
        if not unresolved:
            return
        now = time.monotonic()
        if now - getattr(self, "_last_node_resolve", 0.0) < 1.0:
            return
        self._last_node_resolve = now
        try:
            from .. import state
            by_aid = {row.get("actor_id"): row.get("node_id")
                      for row in state.list_actors()}
        except Exception:
            return  # transient; next snapshot retries
        newly = 0
        for rep in unresolved:
            handle = (rep.get("gang") or [rep["handle"]])[0]
            nid = by_aid.get(handle._actor_id)  # ids are bytes on the wire
            if nid:
                self._replica_nodes[rep["id"]] = nid
                newly += 1
        if newly:
            # routers that already saw this version must re-pull to get
            # the node annotations, or locality stays off until the next
            # unrelated table change
            self._version += 1

    def snapshot(self, known_version: int = -1) -> Optional[dict]:
        """Routing table if newer than known_version (long-poll pull)."""
        # Reconcile drains on the POLL path too (throttled): when every
        # replica of a deployment is evicted, completions — and with
        # them report_metrics — stop entirely, but failing routers keep
        # polling snapshot; without this hook the stale annotations
        # would never refresh and the outage would be permanent.
        self._maybe_evacuate_draining()
        self._maybe_autoscale()
        if known_version == self._version:
            return None
        self._resolve_replica_nodes()
        now = time.monotonic()
        table = {}
        for name, entry in self._deployments.items():
            table[name] = {
                "route_prefix": entry.get("route_prefix"),
                "ingress": entry["config"].get("ingress", False),
                "max_concurrent_queries":
                    entry["config"].get("max_concurrent_queries", 8),
                # boot-EWMA Retry-After while a scale-up is in flight:
                # routers stamp it on typed sheds so clients re-arrive
                # as the new replica lands
                "scaleup_retry_after_s":
                    self._scaleup_retry_after(name, now),
                "replicas": [{"id": r["id"], "handle": r["handle"],
                              "node_id":
                                  self._replica_nodes.get(r["id"]),
                              # retiring (autoscale drain-down): keep
                              # sid-sticky session ops flowing, take no
                              # NEW sessions
                              "draining": bool(r.get("retiring"))}
                             for r in entry["replicas"]],
            }
        return {"version": self._version, "table": table}

    def list_deployments(self) -> Dict[str, dict]:
        return {name: {"num_replicas": len(e["replicas"]),
                       "route_prefix": e.get("route_prefix"),
                       "config": {k: v for k, v in e["config"].items()
                                  if k != "ray_actor_options"}}
                for name, e in self._deployments.items()}

    # -- autoscaling --------------------------------------------------------
    def report_metrics(self, name: str, ongoing_per_replica) -> bool:
        """Router-reported in-flight counts: the occupancy fallback for
        deployments without a decode engine, and one of the tick
        sources of the autoscale loop.  ``ongoing_per_replica`` is a
        {replica_id: in_flight} mapping (older routers sent a bare
        list; tolerated)."""
        self._maybe_reconcile_proxies()
        self._maybe_heal_replicas()     # 5s-throttled internally
        self._maybe_evacuate_draining()  # 2s-throttled internally
        self._resolve_replica_nodes()   # 1s-throttled internally
        entry = self._deployments.get(name)
        if entry is None:
            return False
        if not isinstance(ongoing_per_replica, dict):
            ongoing_per_replica = {
                r["id"]: c for r, c in zip(entry["replicas"],
                                           ongoing_per_replica or [])}
        entry["metrics"] = {"ongoing": dict(ongoing_per_replica),
                            "ts": time.monotonic()}
        self._maybe_autoscale()         # interval-throttled internally
        return True

    def autoscale_tick(self) -> bool:
        """Explicit loop nudge (HTTP proxies schedule one per
        serve_autoscale_interval_s): keeps the autoscaler — and the
        piggybacked heal/evacuate reconciles — ticking through idle
        valleys, when no request traffic is polling snapshots, so
        scale-DOWN to min_replicas happens without a client trickle."""
        self._maybe_reconcile_proxies()
        self._maybe_heal_replicas()
        self._maybe_evacuate_draining()
        self._maybe_autoscale()
        return True

    def _maybe_autoscale(self) -> None:
        """One pass of the autoscale loop, throttled to
        serve_autoscale_interval_s: fold boot observations, advance
        in-flight retirements, then decide each autoscaled deployment
        via the pure policy (serve/autoscaler.py) over engine
        occupancy series (metrics history) or router-reported counts."""
        from ..core.config import GlobalConfig
        iv = GlobalConfig.serve_autoscale_interval_s
        if iv is None or iv <= 0:
            return
        now = time.monotonic()
        if now - self._last_autoscale < iv:
            return
        self._last_autoscale = now
        self._observe_boots(now)
        self._tick_retirements(now)
        autoscaled = [name for name, e in self._deployments.items()
                      if e["config"].get("autoscaling_config")]
        if autoscaled:
            hist = self._engine_history()
            for name in autoscaled:
                entry = self._deployments.get(name)
                if entry is None:
                    continue
                try:
                    self._autoscale_one(name, entry, now, hist)
                except Exception:
                    # chaos 'error' action or a transient state-API
                    # failure: the decision is re-derived next tick
                    pass
        self._push_deployment_metrics()

    @staticmethod
    def _engine_history() -> Dict[str, Any]:
        """Latest engine-pushed serve gauges from every process's
        metrics-history ring (state.metrics_history plumbing): the
        occupancy/waiting signal for engine deployments.  One fetch
        per tick, shared by every deployment's decision."""
        try:
            from .. import state
            return state.metrics_history(last=4)
        except Exception:
            return {}

    def _latest_engine_gauges(self, hist: Dict[str, Any],
                              name: str) -> Dict[str, Dict[str, float]]:
        """{replica_id: {occupied, waiting, max_slots}} from the newest
        history sample carrying this deployment's label."""
        from ..core import metrics_history as mh
        out: Dict[str, Dict[str, float]] = {}
        fam = {"occupied": "ray_tpu_serve_engine_occupied_slots",
               "waiting": "ray_tpu_serve_engine_waiting_sessions",
               "max_slots": "ray_tpu_serve_engine_max_slots"}
        for proc in (hist.get("processes") or {}).values():
            samples = proc.get("samples") or []
            for field, metric in fam.items():
                for pt in mh.series(samples, metric, kind="gauges",
                                    labels={"deployment": name}):
                    rid = mh.parse_labels(pt["key"]).get("replica")
                    if not rid:
                        continue
                    slot = out.setdefault(rid, {})
                    # series is time-ordered: the last write wins
                    slot[field] = float(pt["value"])
        return out

    def _autoscale_one(self, name: str, entry: Dict[str, Any],
                       now: float, hist: Dict[str, Any]) -> None:
        import collections

        from . import autoscaler
        auto = entry["config"]["autoscaling_config"]
        gauges = self._latest_engine_gauges(hist, name)
        ongoing = (entry.get("metrics") or {}).get("ongoing") or {}
        target_per = float(auto.get(
            "target_num_ongoing_requests_per_replica", 2.0) or 2.0)
        views = []
        for rep in entry["replicas"]:
            rid = rep["id"]
            g = gauges.get(rid)
            if g and g.get("max_slots"):
                occupied = g.get("occupied", 0.0)
                waiting = g.get("waiting", 0.0)
                capacity = g["max_slots"]
            else:
                occupied = float(ongoing.get(rid, 0.0))
                waiting = 0.0
                capacity = max(target_per, 0.1)
            views.append(autoscaler.ReplicaView(
                replica_id=rid, occupied=occupied, waiting=waiting,
                capacity=capacity,
                suspect=self._replica_nodes.get(rid)
                in self._suspect_nodes,
                retiring=bool(rep.get("retiring"))))
        ring = entry.setdefault(
            "signal", collections.deque(maxlen=600))
        ring.append(autoscaler.fleet_sample(
            now, views, float(auto.get("suspect_weight", 0.25) or 0.0)))
        decision = autoscaler.decide(
            auto, views, list(ring), now,
            last_up=entry.get("as_last_up", 0.0),
            last_down=entry.get("as_last_down", 0.0))
        cur = sum(1 for v in views if not v.retiring)
        if decision.target == cur:
            return
        # chaos site: delay or drop the DECISION itself (`ray-tpu chaos
        # validate` knows it).  A dropped decision is simply re-derived
        # next tick from current state — targets are absolute, so a
        # retried decision can never double-scale.
        from ..util import fault_injection as fi
        if fi.ACTIVE is not None:
            act = fi.ACTIVE.point("serve.autoscale", name)
            if act is not None:
                if act["action"] in ("delay", "latency"):
                    time.sleep(max(0.0, act["delay_s"]))
                elif act["action"] == "drop":
                    return
                else:
                    raise RuntimeError(
                        f"chaos: injected serve.autoscale failure for "
                        f"{name}")
        self._apply_decision(name, entry, decision, cur, now)

    def _apply_decision(self, name: str, entry: Dict[str, Any],
                        decision, cur: int, now: float) -> None:
        target = decision.target
        try:
            from .. import state
            state.report_event(
                f"serve: autoscale {name!r} {cur} -> {target} "
                f"({decision.reason})", severity="INFO", source="serve")
        except Exception:
            pass
        if target > cur:
            for _ in range(target - cur):
                self._start_replica(name, entry)
            entry["as_last_up"] = now
            entry["as_dec_up"] = entry.get("as_dec_up", 0) + 1
        else:
            victims = list(decision.victims) or [
                r["id"] for r in reversed(entry["replicas"])
                if not r.get("retiring")]
            for rid in victims[:cur - target]:
                self._begin_retirement(name, entry, rid, now)
            entry["as_last_down"] = now
            entry["as_dec_down"] = entry.get("as_dec_down", 0) + 1
        entry["config"]["num_replicas"] = target
        entry["last_scaled"] = now
        self._version += 1

    def _begin_retirement(self, name: str, entry: Dict[str, Any],
                          rid: str, now: float) -> None:
        """Scale-down via the drain path: the victim stops taking NEW
        sessions (its engine sheds starts; routers skip it via the
        snapshot's ``draining`` flag) while live streams keep their
        sid-sticky access until they migrate — the failover client
        re-admits each one elsewhere on the ``migrating`` reply.  The
        kill happens in _tick_retirements at live_sessions == 0 (or
        the migration deadline)."""
        from .. import api
        from ..core.config import GlobalConfig
        rep = next((r for r in entry["replicas"] if r["id"] == rid),
                   None)
        if rep is None or rid in self._retiring \
                or rid in self._evacuations:
            return
        rep["retiring"] = True
        # doomed replicas sit LAST so an unrelated _scale_to pops them
        # first, never a serving replica
        entry["replicas"].remove(rep)
        entry["replicas"].append(rep)
        self._retiring[rid] = {
            "name": name,
            "deadline": now + GlobalConfig.serve_session_migration_timeout_s}
        if not rep.get("gang"):
            try:
                api.get(rep["handle"].prepare_drain.remote(),
                        timeout=10.0)
            except Exception:
                pass   # dead/hung replica: the deadline covers it

    def _tick_retirements(self, now: float) -> None:
        from .. import api
        for rid, info in list(self._retiring.items()):
            entry = self._deployments.get(info["name"])
            rep = None if entry is None else next(
                (r for r in entry["replicas"] if r["id"] == rid), None)
            if rep is None:
                self._retiring.pop(rid, None)
                continue   # deleted / healed / scaled under us
            live = 0
            if now < info["deadline"] and not rep.get("gang"):
                try:
                    live = api.get(rep["handle"].drain_status.remote(),
                                   timeout=5.0).get("live_sessions", 0)
                except Exception:
                    live = 0
            if live > 0 and now < info["deadline"]:
                continue   # sessions still migrating; next tick
            entry["replicas"].remove(rep)
            self._replica_nodes.pop(rid, None)
            self._boot_pending.pop(rid, None)
            self._audit_kill(info["name"], rid, -3)
            if rep.get("gang"):
                from .gang import stop_gang_replica
                try:
                    stop_gang_replica(rep)
                except Exception:
                    pass
            else:
                try:
                    api.kill(rep["handle"])
                except Exception:
                    pass
            self._retiring.pop(rid, None)
            self._version += 1

    def _observe_boots(self, now: float) -> None:
        """Fold completed replica boots (start -> ALIVE in the actor
        table) into the boot-time EWMA behind scale-up Retry-After
        hints."""
        if not self._boot_pending:
            return
        try:
            from .. import state
            alive = {row["actor_id"] for row in state.list_actors()
                     if row.get("state") == "ALIVE"}
        except Exception:
            return
        by_rid: Dict[str, Any] = {}
        for entry in self._deployments.values():
            for rep in entry["replicas"]:
                by_rid[rep["id"]] = (rep.get("gang")
                                     or [rep["handle"]])[0]
        from ..core.config import GlobalConfig
        alpha = min(1.0, max(
            0.01, GlobalConfig.serve_replica_boot_ewma_alpha))
        for rid, t0 in list(self._boot_pending.items()):
            handle = by_rid.get(rid)
            if handle is None or now - t0 > 600.0:
                self._boot_pending.pop(rid, None)   # gone or wedged
                continue
            if handle._actor_id in alive:
                boot = max(0.1, now - t0)
                self._boot_ewma = boot if self._boot_ewma is None else \
                    alpha * boot + (1.0 - alpha) * self._boot_ewma
                self._boot_pending.pop(rid, None)

    def _scaleup_retry_after(self, name: str, now: float
                             ) -> Optional[float]:
        """Retry-After for sheds while this deployment's scale-up is in
        flight: the EWMA boot time minus how long the oldest pending
        replica has already been booting — clients re-arrive right as
        capacity lands instead of on the generic backoff floor."""
        pending = [t0 for rid, t0 in self._boot_pending.items()
                   if rid.rsplit("#", 1)[0] == name]
        if not pending or self._boot_ewma is None:
            return None
        return max(0.5, self._boot_ewma - (now - min(pending)))

    def _push_deployment_metrics(self) -> None:
        """Replica-count + decision samples to this worker's nodelet
        (same ``serve_metrics`` plumbing the engines use), so metrics
        history carries the replica-count-vs-load timeline."""
        try:
            import asyncio

            from ..core.worker_runtime import current_worker_runtime
            rt = current_worker_runtime()
            if rt is None or rt._loop is None:
                return
            for name, entry in self._deployments.items():
                payload: Dict[str, Any] = {
                    "deployment": name,
                    "replicas": sum(1 for r in entry["replicas"]
                                    if not r.get("retiring"))}
                up = entry.pop("as_dec_up", 0)
                down = entry.pop("as_dec_down", 0)
                if up:
                    payload["decisions_up"] = up
                if down:
                    payload["decisions_down"] = down
                asyncio.run_coroutine_threadsafe(
                    rt.nodelet.notify("serve_metrics", payload),
                    rt._loop)
        except Exception:
            pass
