"""@serve.deployment: declarative deployment definitions.

Capability mirror of the reference's `serve/deployment.py` +
`serve/api.py:455` — a Deployment wraps the user class/function with
replica/runtime options; `serve.run(deployment)` materializes it via the
controller.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

from .config import AutoscalingConfig, DeploymentConfig


@dataclasses.dataclass
class Deployment:
    func_or_class: Callable
    name: str
    config: DeploymentConfig
    init_args: tuple = ()
    init_kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    route_prefix: Optional[str] = None

    def options(self, *, num_replicas: Optional[int] = None,
                max_concurrent_queries: Optional[int] = None,
                user_config: Any = None,
                autoscaling_config: Optional[AutoscalingConfig] = None,
                ray_actor_options: Optional[Dict[str, Any]] = None,
                route_prefix: Optional[str] = "__keep__",
                name: Optional[str] = None,
                gang_size: Optional[int] = None,
                gang_mesh: Optional[str] = None,
                gang_strategy: Optional[str] = None) -> "Deployment":
        cfg = dataclasses.replace(self.config)
        if num_replicas is not None:
            cfg.num_replicas = num_replicas
        if max_concurrent_queries is not None:
            cfg.max_concurrent_queries = max_concurrent_queries
        if user_config is not None:
            cfg.user_config = user_config
        if autoscaling_config is not None:
            cfg.autoscaling_config = autoscaling_config
        if ray_actor_options is not None:
            cfg.ray_actor_options = ray_actor_options
        if gang_size is not None:
            cfg.gang_size = gang_size
        if gang_mesh is not None:
            cfg.gang_mesh = gang_mesh
        if gang_strategy is not None:
            cfg.gang_strategy = gang_strategy
        return dataclasses.replace(
            self, config=cfg,
            name=name or self.name,
            route_prefix=(self.route_prefix if route_prefix == "__keep__"
                          else route_prefix))

    def bind(self, *args, **kwargs) -> "Deployment":
        """Capture init args (the deployment-graph entry point)."""
        return dataclasses.replace(self, init_args=args,
                                   init_kwargs=kwargs)

    def __call__(self, *a, **kw):
        raise RuntimeError(
            "deployments are not callable directly; use serve.run() and a "
            "handle")


def deployment(_func_or_class: Optional[Callable] = None, *,
               name: Optional[str] = None, num_replicas: int = 1,
               max_concurrent_queries: int = 8,
               user_config: Any = None,
               autoscaling_config: Optional[AutoscalingConfig] = None,
               ray_actor_options: Optional[Dict[str, Any]] = None,
               route_prefix: Optional[str] = None,
               gang_size: int = 1, gang_mesh: Optional[str] = None,
               gang_strategy: str = "PACK"):
    def wrap(fc: Callable) -> Deployment:
        cfg = DeploymentConfig(
            num_replicas=num_replicas,
            max_concurrent_queries=max_concurrent_queries,
            user_config=user_config,
            autoscaling_config=autoscaling_config,
            ray_actor_options=ray_actor_options or {},
            gang_size=gang_size, gang_mesh=gang_mesh,
            gang_strategy=gang_strategy)
        return Deployment(fc, name or fc.__name__, cfg,
                          route_prefix=route_prefix)

    return wrap(_func_or_class) if _func_or_class is not None else wrap
