"""HTTP ingress deployments: sub-path routing inside a deployment.

Capability mirror of the reference's ``serve.ingress`` (serve/api.py —
bind a FastAPI app so one deployment serves many routes/methods).
FastAPI is not in this image, so the TPU-native shape is a lightweight
route table: decorate methods with :func:`route` and the class with
:func:`ingress`; the HTTP proxy forwards the full request context
(sub-path, method, query, body) to ingress deployments, and the
generated ``__call__`` dispatches.

    @serve.deployment
    @serve.ingress
    class Api:
        @serve.route("/items", methods=("GET",))
        def list_items(self, request):
            return {"items": [...], "q": request["query"]}

        @serve.route("/items", methods=("POST",))
        def add_item(self, request):
            return {"added": request["body"]}

``request`` is ``{"path", "method", "query", "body"}`` where ``path``
is the remainder AFTER the deployment's route prefix.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Sequence

#: key the proxy uses to ship http context to ingress deployments
HTTP_KEY = "__http__"


def route(path: str, methods: Sequence[str] = ("GET", "POST")):
    """Mark a method as handling ``path`` (exact or prefix of deeper
    paths) for the given HTTP methods."""
    if not path.startswith("/"):
        raise ValueError(f"route path must start with '/' (got {path!r})")

    def deco(fn: Callable) -> Callable:
        routes = getattr(fn, "_serve_routes", [])
        fn._serve_routes = routes + [
            (path, tuple(m.upper() for m in methods))]
        return fn
    return deco


def ingress(cls):
    """Class decorator wiring the route table into ``__call__``."""
    if not isinstance(cls, type):
        raise TypeError("@serve.ingress decorates a class (apply it "
                        "UNDER @serve.deployment)")
    table = []          # (path, methods, attr_name)
    seen = set()
    # walk the MRO: routes inherited from base classes are routes too
    # (nearest definition wins, like normal attribute lookup)
    for klass in cls.__mro__:
        for attr_name, attr in vars(klass).items():
            if attr_name in seen:
                continue
            seen.add(attr_name)
            for path, methods in getattr(attr, "_serve_routes", ()):
                table.append((path, methods, attr_name))
    if not table:
        raise ValueError(
            "@serve.ingress found no @serve.route-decorated methods "
            f"on {cls.__name__}")
    # longest prefix wins, like the proxy's own route matching
    table.sort(key=lambda t: -len(t[0]))

    def __call__(self, request: Any):
        ctx = request.get(HTTP_KEY) if isinstance(request, dict) else None
        if ctx is None:
            raise TypeError(
                f"{cls.__name__} is an ingress deployment: call it over "
                "HTTP (the proxy supplies the request context), not "
                "through a bare handle payload")
        path, method = ctx["path"] or "/", ctx["method"].upper()
        allowed_elsewhere = False
        for rpath, methods, attr in table:
            if path == rpath or path.startswith(
                    rpath.rstrip("/") + "/"):
                if method in methods:
                    return getattr(self, attr)(ctx)
                allowed_elsewhere = True
        if allowed_elsewhere:
            return {"error": f"method {method} not allowed for {path}",
                    "status": 405}
        return {"error": f"no route for {path}", "status": 404}

    cls.__call__ = __call__
    cls._serve_ingress = True
    return cls
