"""Declarative Serve config: YAML/dict schema -> running deployments.

Capability mirror of the reference's Serve REST schema + declarative CLI
(`python/ray/serve/schema.py:1` ServeApplicationSchema/ServeDeploySchema;
`serve deploy` / `serve status` / `serve config` round trip).  A config
names applications by ``import_path`` ("module:attribute" resolving to a
``@serve.deployment`` object); per-deployment overrides layer on top of
the code-declared options.  The submitted config is stored in the
cluster KV so any process — the CLI, the dashboard — can read back what
was deployed (the reference keeps it in the Serve controller).
"""

from __future__ import annotations

import dataclasses
import importlib
import json
from typing import Any, Dict, List, Optional

_KV_NS = "serve"
_KV_CONFIG_KEY = b"deploy_config"


class SchemaError(ValueError):
    """A config that does not match the schema, with a field path."""


def _require(cond: bool, path: str, msg: str) -> None:
    if not cond:
        raise SchemaError(f"{path}: {msg}")


@dataclasses.dataclass
class DeploymentOverride:
    """Per-deployment overrides (reference: DeploymentSchema)."""

    name: str
    num_replicas: Optional[int] = None
    max_concurrent_queries: Optional[int] = None
    user_config: Any = None
    autoscaling_config: Optional[Dict[str, Any]] = None
    ray_actor_options: Optional[Dict[str, Any]] = None
    gang_size: Optional[int] = None
    gang_mesh: Optional[str] = None
    gang_strategy: Optional[str] = None

    @classmethod
    def from_dict(cls, d: Dict[str, Any], path: str) -> "DeploymentOverride":
        _require(isinstance(d, dict), path, f"expected a mapping, got {d!r}")
        _require("name" in d, path, "deployment entry needs a 'name'")
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        _require(not unknown, path, f"unknown field(s) {sorted(unknown)}")
        out = cls(**d)
        if out.num_replicas is not None:
            _require(int(out.num_replicas) >= 0, f"{path}.num_replicas",
                     "must be >= 0")
        if out.autoscaling_config is not None:
            _require(isinstance(out.autoscaling_config, dict),
                     f"{path}.autoscaling_config", "must be a mapping")
        return out


@dataclasses.dataclass
class ApplicationConfig:
    """One application (reference: ServeApplicationSchema)."""

    import_path: str
    name: Optional[str] = None
    route_prefix: Optional[str] = "__derive__"
    args: Optional[List[Any]] = None
    kwargs: Optional[Dict[str, Any]] = None
    deployments: List[DeploymentOverride] = dataclasses.field(
        default_factory=list)

    @classmethod
    def from_dict(cls, d: Dict[str, Any], path: str) -> "ApplicationConfig":
        _require(isinstance(d, dict), path, f"expected a mapping, got {d!r}")
        _require("import_path" in d, path, "application needs 'import_path'")
        ip = d["import_path"]
        _require(isinstance(ip, str) and ":" in ip, f"{path}.import_path",
                 "must be 'module:attribute'")
        deps = [DeploymentOverride.from_dict(x, f"{path}.deployments[{i}]")
                for i, x in enumerate(d.get("deployments") or [])]
        known = {"import_path", "name", "route_prefix", "args", "kwargs",
                 "deployments"}
        unknown = set(d) - known
        _require(not unknown, path, f"unknown field(s) {sorted(unknown)}")
        return cls(import_path=ip, name=d.get("name"),
                   route_prefix=d.get("route_prefix", "__derive__"),
                   args=d.get("args"), kwargs=d.get("kwargs"),
                   deployments=deps)

    def resolve_target(self):
        """Import the deployment object this application names."""
        mod_name, _, attr = self.import_path.partition(":")
        mod = importlib.import_module(mod_name)
        target = mod
        for part in attr.split("."):
            target = getattr(target, part)
        return target


@dataclasses.dataclass
class DeployConfig:
    """Top-level config (reference: ServeDeploySchema)."""

    applications: List[ApplicationConfig]

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "DeployConfig":
        _require(isinstance(d, dict), "<root>",
                 f"expected a mapping, got {d!r}")
        if "applications" in d:
            apps_raw = d["applications"]
            _require(isinstance(apps_raw, list) and apps_raw,
                     "applications", "must be a non-empty list")
            apps = [ApplicationConfig.from_dict(a, f"applications[{i}]")
                    for i, a in enumerate(apps_raw)]
        else:
            # single-application shorthand: import_path at the top level
            apps = [ApplicationConfig.from_dict(d, "<root>")]
        names = [a.name or a.import_path for a in apps]
        _require(len(names) == len(set(names)), "applications",
                 f"duplicate application names in {names}")
        return cls(applications=apps)

    def to_dict(self) -> Dict[str, Any]:
        apps = []
        for a in self.applications:
            d = {k: v for k, v in dataclasses.asdict(a).items()
                 if v not in (None, [], {})}
            # an EXPLICIT route_prefix=None means handle-only (no HTTP
            # route) — dropping it would silently turn the deployment
            # HTTP-exposed on a config re-apply; only the "__derive__"
            # default is elidable
            if a.route_prefix is None:
                d["route_prefix"] = None
            elif a.route_prefix == "__derive__":
                d.pop("route_prefix", None)
            apps.append(d)
        return {"applications": apps}


def load_config(source: Any) -> DeployConfig:
    """Accepts a dict, a YAML/JSON string, or a path to a YAML file."""
    if isinstance(source, DeployConfig):
        return source
    if isinstance(source, dict):
        return DeployConfig.from_dict(source)
    if isinstance(source, str):
        import os

        import yaml
        if os.path.exists(source):
            with open(source) as f:
                return DeployConfig.from_dict(yaml.safe_load(f))
        return DeployConfig.from_dict(yaml.safe_load(source))
    raise SchemaError(f"unsupported config source {type(source)}")


def _apply_overrides(dep, override: DeploymentOverride):
    kw: Dict[str, Any] = {}
    if override.num_replicas is not None:
        kw["num_replicas"] = override.num_replicas
    if override.max_concurrent_queries is not None:
        kw["max_concurrent_queries"] = override.max_concurrent_queries
    if override.user_config is not None:
        kw["user_config"] = override.user_config
    if override.autoscaling_config is not None:
        from .config import AutoscalingConfig
        kw["autoscaling_config"] = AutoscalingConfig(
            **override.autoscaling_config)
    if override.ray_actor_options is not None:
        kw["ray_actor_options"] = override.ray_actor_options
    for g in ("gang_size", "gang_mesh", "gang_strategy"):
        v = getattr(override, g)
        if v is not None:
            kw[g] = v
    return dep.options(**kw) if kw else dep


def apply_config(source: Any) -> Dict[str, Any]:
    """Deploy every application in the config; returns {app: handle}.

    Declarative semantics: applying a config REPLACES what it names
    (redeploy restarts replicas with the new options) and records the
    config in the cluster KV for `serve config` / `serve status`.
    """
    from . import api as serve_api
    from .deployment import Deployment

    cfg = load_config(source)
    handles: Dict[str, Any] = {}
    for app in cfg.applications:
        target = app.resolve_target()
        if not isinstance(target, Deployment):
            raise SchemaError(
                f"{app.import_path} resolved to {type(target).__name__}; "
                "expected a @serve.deployment object")
        if app.args or app.kwargs:
            target = target.bind(*(app.args or ()),
                                 **(app.kwargs or {}))
        matched = [o for o in app.deployments
                   if o.name in (target.name, app.name)]
        unmatched = [o.name for o in app.deployments if o not in matched]
        if unmatched:
            # a typo'd override silently not taking effect is the worst
            # failure mode of declarative config — make it loud
            raise SchemaError(
                f"application {app.name or app.import_path!r}: deployment "
                f"override(s) {unmatched} match neither the target "
                f"deployment {target.name!r} nor the application name")
        for override in matched:
            target = _apply_overrides(target, override)
        name = app.name or target.name
        handles[name] = serve_api.run(target, name=name,
                                      route_prefix=app.route_prefix)
    from ..util import kv
    kv.kv_put(_KV_CONFIG_KEY, json.dumps(cfg.to_dict()).encode(),
              namespace=_KV_NS)
    return handles


def get_deployed_config() -> Optional[Dict[str, Any]]:
    """The last config applied to this cluster (reference: serve config)."""
    from ..util import kv
    raw = kv.kv_get(_KV_CONFIG_KEY, namespace=_KV_NS)
    return json.loads(raw) if raw else None


def status() -> Dict[str, Any]:
    """Application-rolled-up status (reference: serve status CLI)."""
    from . import api as serve_api
    table = serve_api.status_table()
    deployed = get_deployed_config()
    apps: Dict[str, Any] = {}
    for name, info in table.items():
        healthy = info.get("num_replicas", 0) >= 1 or \
            info.get("config", {}).get("num_replicas", 1) == 0
        apps[name] = {
            "status": "RUNNING" if healthy else "DEPLOYING",
            "deployment": info,
        }
    return {"applications": apps,
            "config": deployed,
            "proxies": serve_api.proxy_statuses()}
