"""Shared-prefix index: a radix trie over token sequences.

Chat traffic is prefix-heavy — thousands of sessions share one system
prompt, and a new session whose prompt extends a prefix that is already
resident in SOME KV cache only needs to prefill the unshared suffix
(vLLM's automatic-prefix-caching insight; arXiv:2605.25645 prices why
this matters on TPU serving).  Two layers consult this index:

* **Engine-side** (`decode_session.ContinuousBatchingEngine`): keys are
  the prompts of live decode slots, values are slot indices.  Admission
  looks up the longest shared prefix, copies that many K/V rows out of
  the donor slot (`models.cache_gather_slot`), and chunk-prefills only
  the suffix — prefix-hit TTFT drops to O(suffix) instead of O(prompt).
* **Router-side** (`serve/router.py`): keys are recently-routed session
  prompts, values are replica ids.  New sessions are placed by
  least-occupancy with prefix AFFINITY as the tie-break, so sessions
  sharing a system prompt land where the prefix is hot in the first
  place instead of warming every replica independently.

The trie is a plain compressed-enough radix over int tokens (children
are dicts keyed by the next token), values are opaque owner ids, and
every owner has at most one key — re-inserting an owner replaces its
old key (a reclaimed slot, a replica that moved).  All operations are
O(len(key)); the structure is lock-free by contract (engine thread /
router lock own their instance).
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Tuple


class _Node:
    __slots__ = ("children", "owners")

    def __init__(self):
        self.children: Dict[int, "_Node"] = {}
        # owners whose key passes THROUGH this node (id -> key length
        # at which the owner's key ends, if it ends here; 0 otherwise
        # is never stored — we store only terminal depths per owner on
        # the path for O(1) cleanup)
        self.owners: set = set()


class PrefixIndex:
    """Radix/trie shared-prefix index mapping token sequences to owner
    ids (engine slots, replica ids), with longest-match lookup and
    hit/miss accounting."""

    def __init__(self, max_owners: int = 0):
        self._root = _Node()
        self._keys: Dict[Any, Tuple[int, ...]] = {}   # owner -> key
        self._max_owners = int(max_owners)
        self.hits = 0           # lookups that matched >= 1 token
        self.misses = 0
        self.tokens_matched = 0  # total prefix tokens served from hits

    # ------------------------------------------------------------- mutation

    def insert(self, tokens: Iterable[int], owner: Any) -> None:
        """Register ``owner`` as holding ``tokens``.  An owner holds at
        most one key: re-insertion evicts its previous key first (slot
        reuse, replica re-route).  When ``max_owners`` is set, the
        OLDEST owner is evicted past the bound (insertion-ordered dict
        = LRU-by-insert, matching engine slot lifetimes)."""
        key = tuple(int(t) for t in tokens)
        if owner in self._keys:
            self.evict(owner)
        if not key:
            return
        if self._max_owners and len(self._keys) >= self._max_owners:
            oldest = next(iter(self._keys))
            self.evict(oldest)
        node = self._root
        node.owners.add(owner)
        for t in key:
            node = node.children.setdefault(t, _Node())
            node.owners.add(owner)
        self._keys[owner] = key

    def evict(self, owner: Any) -> bool:
        """Drop ``owner``'s key (slot reclaimed, replica gone).  Prunes
        now-ownerless trie branches so memory tracks live owners."""
        key = self._keys.pop(owner, None)
        if key is None:
            return False
        node = self._root
        node.owners.discard(owner)
        path: List[Tuple[_Node, int]] = []
        for t in key:
            nxt = node.children.get(t)
            if nxt is None:       # defensive: trie desynced, stop
                return True
            path.append((node, t))
            node = nxt
            node.owners.discard(owner)
        for parent, t in reversed(path):
            child = parent.children.get(t)
            if child is not None and not child.owners:
                del parent.children[t]
            else:
                break
        return True

    # -------------------------------------------------------------- lookup

    def longest_match(self, tokens: Iterable[int],
                      cap: Optional[int] = None
                      ) -> Tuple[Optional[Any], int]:
        """Walk ``tokens`` down the trie; returns ``(owner, depth)`` for
        the deepest node that still has a live owner (``depth`` = how
        many prefix tokens that owner's key shares with ``tokens``).
        ``cap`` bounds the usable depth (an admission must re-run at
        least the prompt's last token for its logits).  Counts hit/miss
        accounting: a match of zero tokens is a miss."""
        key = [int(t) for t in tokens]
        if cap is not None:
            key = key[:max(0, int(cap))]
        node = self._root
        best: Tuple[Optional[Any], int] = (None, 0)
        depth = 0
        for t in key:
            node = node.children.get(t)
            if node is None:
                break
            depth += 1
            if node.owners:
                best = (next(iter(node.owners)), depth)
        if best[0] is None or best[1] <= 0:
            self.misses += 1
            return (None, 0)
        self.hits += 1
        self.tokens_matched += best[1]
        return best

    # --------------------------------------------------------------- stats

    def __len__(self) -> int:
        return len(self._keys)

    def owners(self) -> List[Any]:
        return list(self._keys)

    def key_of(self, owner: Any) -> Optional[Tuple[int, ...]]:
        return self._keys.get(owner)

    def stats(self) -> Dict[str, Any]:
        total = self.hits + self.misses
        return {"entries": len(self._keys),
                "hits": self.hits, "misses": self.misses,
                "hit_rate": round(self.hits / total, 4) if total else None,
                "tokens_matched": self.tokens_matched}
