"""Router: replica selection with in-flight caps.

Capability mirror of the reference's `Router`/`ReplicaSet`
(`serve/_private/router.py:62,134,221`): round-robin over replicas,
skipping those at ``max_concurrent_queries``; blocks (with backoff) when
all are saturated.  Runs in-process in every handle/proxy; refreshes its
table by polling the controller's versioned snapshot (the long-poll role).
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Dict, Optional

from .. import api


class Router:
    def __init__(self, controller_handle, poll_interval_s: float = 0.25):
        self._controller = controller_handle
        self._version = -1
        self._table: Dict[str, dict] = {}
        self._inflight: Dict[str, int] = {}
        self._rr: Dict[str, Any] = {}
        self._lock = threading.Lock()
        self._poll_interval = poll_interval_s
        self._last_poll = 0.0
        # Locality: prefer replicas on this router's own node (the
        # reference's LocalityScheduling in the replica scheduler) — a
        # per-node proxy then serves node-local traffic without an extra
        # network hop whenever a local replica has capacity.
        try:
            self._node_id = api.get_runtime_context().node_id
        except Exception:
            self._node_id = None
        # Replicas on dead/DRAINING nodes are evicted the moment the
        # controller's `nodes` pubsub event lands — not after the
        # health-check TTL expired (a node death otherwise leaves a
        # window of requests routed to a corpse).
        self._down_nodes: set = set()
        try:
            from .controller import _process_core
            core = _process_core()
            if core is not None:
                core.subscribe_node_events(self._on_node_event)
        except Exception:
            pass  # degraded: the poll TTL + heal loop still converge
        # Prefix affinity (serve/prefix_cache.py): recently routed
        # session prompts -> owning replica.  New sessions sharing a
        # system prompt land where that prefix's KV is already hot, so
        # the replica-side prefix cache hits instead of every replica
        # warming the same prefix independently.  Owners are unique
        # ints (one trie key each); _paff_owner maps them back to
        # replica ids.
        from .prefix_cache import PrefixIndex
        self._paffinity = PrefixIndex(max_owners=512)
        self._paff_owner: Dict[int, str] = {}
        self._paff_seq = 0
        self._refresh(force=True)

    def _on_node_event(self, data) -> None:
        ev = data.get("event")
        if ev in ("dead", "draining", "suspect"):
            # SUSPECT (gray failure / controller-only partition) is
            # routed around exactly like dead/draining — but the node's
            # replicas are NOT torn down, so a rejoin restores them
            nid = data.get("node_id")
            if nid:
                with self._lock:
                    self._down_nodes.add(nid)
        elif ev == "rejoined":
            nid = data.get("node_id")
            if nid:
                with self._lock:
                    self._down_nodes.discard(nid)
        elif ev == "added":
            nid = (data.get("node") or {}).get("id")
            if nid:
                with self._lock:
                    self._down_nodes.discard(nid)

    def _refresh(self, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._last_poll < self._poll_interval:
            return
        self._last_poll = now
        snap = api.get(self._controller.snapshot.remote(self._version),
                       timeout=30.0)
        if snap is None:
            return
        with self._lock:
            self._version = snap["version"]
            self._table = snap["table"]
            self._rr = {name: itertools.cycle(range(
                max(len(e["replicas"]), 1)))
                for name, e in self._table.items()}
            # affinity entries pointing at replicas that left the
            # table are dead weight: evict them
            live = {r["id"] for e in self._table.values()
                    for r in e["replicas"]}
            for owner, rid in list(self._paff_owner.items()):
                if rid not in live:
                    self._paffinity.evict(owner)
                    self._paff_owner.pop(owner, None)

    def deployment_names(self):
        self._refresh()
        return list(self._table)

    def route_prefixes(self) -> Dict[str, str]:
        """deployment -> actual route prefix (HTTP-exposed only)."""
        self._refresh()
        return {name: e["route_prefix"] for name, e in self._table.items()
                if e.get("route_prefix")}

    def match_route(self, path: str) -> Optional[str]:
        self._refresh()
        best = None
        for name, entry in self._table.items():
            prefix = entry.get("route_prefix")
            if prefix is None:
                continue  # handle-only deployment: no HTTP route
            if path == prefix or path.startswith(prefix.rstrip("/") + "/"):
                if best is None or len(prefix) > len(best[1]):
                    best = (name, prefix)
        return best[0] if best else None

    def route_info(self, name: str) -> dict:
        """Deployment routing metadata for the proxy: prefix + whether
        it takes the full http context (@serve.ingress)."""
        self._refresh()
        entry = self._table.get(name, {})
        return {"route_prefix": entry.get("route_prefix"),
                "ingress": entry.get("ingress", False)}

    def _prefix_note(self, tokens, rid: str) -> None:
        """Remember that ``rid`` just admitted a session with this
        prompt — the affinity signal for later sessions sharing its
        prefix.  Caller holds the lock."""
        self._paff_seq += 1
        self._paffinity.insert(tokens, self._paff_seq)
        self._paff_owner[self._paff_seq] = rid
        if len(self._paff_owner) > len(self._paffinity) + 16:
            livemap = set(self._paffinity.owners())
            self._paff_owner = {o: r for o, r
                                in self._paff_owner.items()
                                if o in livemap}

    def _prefix_prefer(self, tokens) -> Optional[str]:
        """Replica holding the longest shared prefix with ``tokens``
        (None on a miss).  Caller holds the lock."""
        owner, depth = self._paffinity.longest_match(tokens)
        if owner is None or depth <= 0:
            return None
        return self._paff_owner.get(owner)

    def assign_request(self, name: str, args: tuple, kwargs: dict,
                       method: Optional[str] = None,
                       timeout_s: float = 60.0,
                       sticky_replica_id: Optional[str] = None,
                       prefix_tokens=None):
        """Pick a non-saturated replica round-robin and return the result
        ObjectRef; counts in-flight per replica.

        ``sticky_replica_id`` pins the request to ONE replica (decode
        sessions: a session's KV cache lives on the replica that ran
        `start`, so its `next_chunk`/`end` must land there — never on a
        load-balancing pass).  A sticky request waits out a saturated
        owner but NEVER spills to a sibling; a vanished owner (scale
        down, crash) raises ReplicaUnavailableError after one forced
        table refresh, because the session's KV cache died with it —
        the proxy-side failover client (serve/failover.py) then
        re-admits the session on a healthy replica via teacher-forced
        replay of its journal, so the stream survives the owner.

        Graceful degradation: a deployment with ZERO live replicas sheds
        the request immediately with the typed ReplicaUnavailableError
        (confirmed against a force-refreshed table first) — holding it
        until the deadline would just stack up doomed requests while the
        deployment restarts.  When replicas exist but all are at their
        in-flight cap, waits under capped exponential backoff with full
        jitter instead of the old fixed 10 ms busy-poll."""
        from ..core.config import GlobalConfig
        from ..exceptions import ReplicaUnavailableError
        from ..util.backoff import ExponentialBackoff
        deadline = time.monotonic() + timeout_s
        bo = ExponentialBackoff(base=GlobalConfig.serve_backoff_base_s,
                                cap=GlobalConfig.serve_backoff_cap_s)
        confirmed_empty = False
        while True:
            self._refresh()
            with self._lock:
                entry = self._table.get(name)
                replicas = entry["replicas"] if entry else []
                cap = entry.get("max_concurrent_queries", 8) if entry else 0
                chosen = None
                sticky_gone = False
                if sticky_replica_id is not None:
                    rep = next((r for r in replicas
                                if r["id"] == sticky_replica_id), None)
                    if rep is None or \
                            rep.get("node_id") in self._down_nodes:
                        sticky_gone = True
                    elif self._inflight.get(rep["id"], 0) < cap:
                        chosen = rep
                elif replicas:
                    # Least-loaded with prefix-affinity and local
                    # preference: locality is a TIE-BREAK among the
                    # least-loaded candidates, never a magnet —
                    # preferring any under-cap local replica outright
                    # would funnel all traffic to it while its
                    # siblings idle.  A session start whose prompt
                    # shares a prefix with a recently routed session
                    # prefers THAT replica (its KV prefix is hot) as
                    # long as it is within one request of the least
                    # load — affinity must not defeat load balance.
                    # RR order breaks remaining ties.
                    start = next(self._rr[name]) % len(replicas)
                    candidates = []
                    for off in range(len(replicas)):
                        rep = replicas[(start + off) % len(replicas)]
                        if rep.get("node_id") in self._down_nodes:
                            continue  # dead/draining node: never route
                        if rep.get("draining"):
                            continue  # retiring: no NEW sessions
                        load = self._inflight.get(rep["id"], 0)
                        if load < cap:
                            candidates.append((load, rep))
                    if candidates:
                        min_load = min(load for load, _ in candidates)
                        if prefix_tokens:
                            want = self._prefix_prefer(prefix_tokens)
                            if want is not None:
                                chosen = next(
                                    (rep for load, rep in candidates
                                     if rep["id"] == want
                                     and load <= min_load + 1), None)
                        if chosen is None:
                            best = [rep for load, rep in candidates
                                    if load == min_load]
                            chosen = next(
                                (rep for rep in best if self._node_id and
                                 rep.get("node_id") == self._node_id),
                                best[0])
                if chosen is not None and prefix_tokens:
                    self._prefix_note(prefix_tokens, chosen["id"])
                if chosen is not None:
                    self._inflight[chosen["id"]] = \
                        self._inflight.get(chosen["id"], 0) + 1
            if chosen is not None:
                ref = chosen["handle"].handle_request.remote(
                    args, kwargs, method)
                return ref, chosen["id"]
            # server-derived Retry-After: while a scale-up is in
            # flight the snapshot carries the boot-time EWMA hint, so
            # shed clients re-arrive right as the new capacity lands
            # instead of on the generic backoff floor
            hint = (entry or {}).get("scaleup_retry_after_s") or 1.0
            if sticky_replica_id is not None and sticky_gone:
                # the session's owner is out of the table: one forced
                # refresh guards against staleness, then fail loudly —
                # re-routing would hand the sid to a replica that has
                # no such KV cache
                if confirmed_empty:
                    raise ReplicaUnavailableError(
                        f"{name} (replica {sticky_replica_id} owning "
                        f"this decode session is gone)",
                        retry_after_s=hint)
                confirmed_empty = True
                self._refresh(force=True)
                continue
            if not replicas:
                # unknown deployment or zero live replicas: one forced
                # refresh guards against a stale table (deploy racing the
                # poll TTL), then shed fast with the typed error
                if confirmed_empty:
                    raise ReplicaUnavailableError(name,
                                                  retry_after_s=hint)
                confirmed_empty = True
                self._refresh(force=True)
                continue
            confirmed_empty = False
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"no replica available for {name!r} within "
                    f"{timeout_s}s")
            self._refresh(force=True)
            time.sleep(min(bo.next_delay(),
                           max(0.0, deadline - time.monotonic())))

    def complete(self, name: str, replica_id: str) -> None:
        with self._lock:
            if replica_id in self._inflight:
                self._inflight[replica_id] -= 1
                if self._inflight[replica_id] <= 0:
                    del self._inflight[replica_id]
        self._report(name)

    def _report(self, name: str) -> None:
        entry = self._table.get(name)
        if not entry:
            return
        counts = {r["id"]: self._inflight.get(r["id"], 0)
                  for r in entry["replicas"]}
        try:
            self._controller.report_metrics.remote(name, counts)
        except Exception:
            pass
