"""serve.run / handles / lifecycle (reference: `serve/api.py:455`)."""

from __future__ import annotations

from typing import Any, Dict, Optional, Union

from .. import api as core_api
from ..core.serialization import dumps_function
from ..parallel.coordinator import _free_port
from .config import HTTPOptions
from .controller import ServeController
from .deployment import Deployment
from .handle import ServeHandle
from .router import Router

_state: Dict[str, Any] = {}


def start(http_options: Optional[HTTPOptions] = None, *,
          detached: bool = False) -> None:
    """Boot the controller (and HTTP proxy) if not already running."""
    if "controller" in _state:
        return
    # A Serve instance already running in this CLUSTER (deployed by some
    # other process — `serve-deploy` CLI, dashboard PUT) must be
    # ATTACHED, not re-booted: booting would spawn a second, stray HTTP
    # proxy alongside whatever ingress mode the instance already runs.
    if http_options is None:
        try:
            existing = core_api.get_actor("serve::controller")
        except ValueError:
            existing = None
        if existing is not None:
            _state["controller"] = existing
            _state["router"] = Router(existing)
            table = core_api.get(existing.proxy_table.remote(),
                                 timeout=30.0)
            _state["http_address"] = next(iter(table.values()), None)
            return
    # Named so ANY process (e.g. a graph-driver replica composing other
    # deployments) can resolve the controller and build its own router.
    controller = core_api.remote(ServeController).options(
        num_cpus=0.1, name="serve::controller", lifetime="detached",
        get_if_exists=True).remote()
    _state["controller"] = controller
    _state["router"] = Router(controller)
    http = http_options or HTTPOptions(port=_free_port())
    if http.location == "NoServer":
        return
    if http.location == "EveryNode":
        # Per-node proxy fleet, reconciled by the controller (reference:
        # http_state.py).  ensure_proxies only SPAWNS; each proxy pushes
        # its bound address asynchronously, so wait driver-side until
        # every alive node's proxy has announced.
        import time as _time

        from .. import state as _state_api
        core_api.get(controller.ensure_proxies.remote(
            {"host": http.host, "location": http.location}), timeout=60.0)
        want = {n["id"] for n in _state_api.list_nodes()
                if n.get("alive")}
        deadline = _time.monotonic() + 120.0
        table: Dict[str, str] = {}
        while _time.monotonic() < deadline:
            table = core_api.get(controller.proxy_table.remote(),
                                 timeout=30.0)
            if want.issubset(table):
                break
            _time.sleep(0.25)
        if not table:
            raise RuntimeError("no Serve proxy came up within 120s")
        missing = want - set(table)
        if missing:
            import sys
            print(f"WARNING: Serve proxies missing on node(s) "
                  f"{sorted(missing)} after 120s; ingress is degraded "
                  f"until the controller's reconcile brings them up",
                  file=sys.stderr)
        _state["proxy_table"] = table
        my_node = core_api.get_runtime_context().node_id
        addr = table.get(my_node) or next(iter(table.values()), None)
        _state["http_address"] = addr
        return
    from .http_proxy import HTTPProxy
    proxy = core_api.remote(HTTPProxy).options(
        num_cpus=0.1, max_concurrency=64,
        lifetime="detached").remote(controller, http.host, http.port)
    core_api.get(proxy.healthy.remote(), timeout=30.0)
    _state["proxy"] = proxy
    _state["http_address"] = f"http://{http.host}:{http.port}"
    # adopt under the proxy's OWN node and reported address — HeadOnly
    # placement has no affinity, so the creator's node may be wrong
    proxy_node = core_api.get(proxy.node_id.remote(), timeout=30.0)
    proxy_addr = core_api.get(proxy.address.remote(), timeout=30.0)
    core_api.get(controller.adopt_proxy.remote(
        proxy_node or core_api.get_runtime_context().node_id, proxy,
        proxy_addr), timeout=30.0)


def run(target: Deployment, *, name: Optional[str] = None,
        route_prefix: Optional[str] = "__derive__",
        _blocking: bool = False) -> ServeHandle:
    """Deploy and return a handle (reference `serve.run`)."""
    start()
    dep = target
    if not isinstance(dep, Deployment):
        raise TypeError("serve.run expects a Deployment "
                        "(@serve.deployment-decorated)")
    dep_name = name or dep.name
    # route_prefix semantics (reference serve.run): "__derive__" → the
    # deployment's own prefix or /<name>; an EXPLICIT None → no HTTP route
    # (internal deployments, e.g. graph upstreams, stay handle-only).
    if route_prefix == "__derive__":
        prefix = dep.route_prefix or f"/{dep_name}"
    else:
        prefix = route_prefix
    if prefix is not None and not prefix.startswith("/"):
        # an empty/relative prefix would prefix-match every request path
        raise ValueError(
            f"route_prefix must start with '/' (got {prefix!r}); "
            "use route_prefix=None for a handle-only deployment")
    cfg = {
        "num_replicas": dep.config.num_replicas,
        "max_concurrent_queries": dep.config.max_concurrent_queries,
        "user_config": dep.config.user_config,
        "ray_actor_options": dep.config.ray_actor_options,
        "autoscaling_config": (
            vars(dep.config.autoscaling_config)
            if dep.config.autoscaling_config else None),
        "gang_size": dep.config.gang_size,
        "gang_mesh": dep.config.gang_mesh,
        "gang_strategy": dep.config.gang_strategy,
        # @serve.ingress deployments receive the full http context
        # (path/method/query/body) from the proxy
        "ingress": bool(getattr(dep.func_or_class, "_serve_ingress",
                                False)),
    }
    core_api.get(_state["controller"].deploy.remote(
        dep_name, dumps_function(dep.func_or_class), dep.init_args,
        dep.init_kwargs, cfg, prefix), timeout=120.0)
    return get_handle(dep_name)


def get_handle(name: str) -> ServeHandle:
    if "router" not in _state:
        raise RuntimeError("serve not started")
    return ServeHandle(_state["router"], name)


get_deployment_handle = get_handle


def list_deployments() -> Dict[str, dict]:
    if "controller" not in _state:
        return {}
    return core_api.get(_state["controller"].list_deployments.remote(),
                        timeout=30.0)


def status_table() -> Dict[str, dict]:
    """Deployment table via the NAMED controller, so any driver — the
    dashboard head, the CLI — reports Serve state without having started
    Serve itself (reference: serve REST status / `serve status` CLI)."""
    if "controller" in _state:
        return list_deployments()
    try:
        h = core_api.get_actor("serve::controller")
    except ValueError:
        return {}  # no Serve instance in this cluster
    return core_api.get(h.list_deployments.remote(), timeout=10.0)


def http_address() -> Optional[str]:
    return _state.get("http_address")


def proxy_statuses() -> Dict[str, str]:
    """node_id -> proxy http address (EveryNode mode; reference: `serve
    status` proxies section).  Readable from ANY process via the named
    controller, like `status_table`."""
    h = _state.get("controller")
    if h is None:
        try:
            h = core_api.get_actor("serve::controller")
        except ValueError:
            return {}
    try:
        return core_api.get(h.proxy_table.remote(), timeout=10.0)
    except Exception:
        return {}


def delete(name: str) -> None:
    if "controller" in _state:
        core_api.get(_state["controller"].delete.remote(name),
                     timeout=60.0)


def shutdown() -> None:
    if "controller" in _state:
        try:
            core_api.get(_state["controller"].shutdown_all.remote(),
                         timeout=60.0)
        except Exception:
            pass
        try:
            core_api.get(_state["controller"].stop_proxies.remote(),
                         timeout=30.0)
        except Exception:
            pass
        for key in ("proxy", "controller"):
            h = _state.pop(key, None)
            if h is not None:
                try:
                    core_api.kill(h)
                except Exception:
                    pass
    _state.clear()
