"""Serve fleet autoscaling: the pure decision policy.

The serve controller's loop (`serve/controller.py::_maybe_autoscale`)
gathers signals — per-replica decode-engine occupancy/waiting series
from `state.metrics_history` (pushed by engines, labeled by deployment
and replica), router-reported in-flight counts as the fallback for
plain deployments, and SUSPECT node membership from the `nodes` pubsub
— and hands them to :func:`decide`, a pure function of explicit inputs
(no clocks, no RPCs), so every branch is unit-testable offline:

* **scale up on trends, before saturation sheds**: recent utilization
  over the high watermark, or sessions waiting for slots, grows the
  fleet toward ``target_occupancy`` — clients should never meet the
  admission-backpressure 503 when the trend saw the burst coming;
* **hysteresis + cooldown**: the ``[occupancy_low, occupancy_high]``
  band holds steady, and each direction has its own cooldown, so
  bursty traffic cannot flap replicas (reference:
  serve/_private/autoscaling_policy.py's delay semantics);
* **SUSPECT down-weighting**: a replica on a quarantined (gray) node
  counts at ``suspect_weight`` capacity — the fleet pre-emptively
  grows around a brownout — and suspect replicas are first in line as
  scale-down victims;
* **scale down drains, never drops**: the decision names its victims
  (suspect first, then least-loaded); the controller retires them via
  the PR-3/5 drain path (engine sheds new starts, live sessions
  migrate via the failover client) instead of killing them outright.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class ReplicaView:
    """One replica's share of the fleet signal at decision time."""
    replica_id: str
    # latest demand on this replica: occupied decode slots (engine
    # replicas) or router-reported in-flight requests (plain replicas)
    occupied: float = 0.0
    # sessions queued for admission (engine ``waiting + prefilling``;
    # plain replicas have no queue visibility -> 0)
    waiting: float = 0.0
    # capacity unit: decode slots, or target_num_ongoing_requests_per_
    # replica for plain replicas
    capacity: float = 1.0
    suspect: bool = False       # node quarantined (PR-9 gray failure)
    retiring: bool = False      # already draining out: not capacity


@dataclasses.dataclass(frozen=True)
class FleetSample:
    """One point of the trended series: aggregate utilization of the
    fleet at ``ts`` (occupied / weighted capacity) plus total waiting
    depth.  The controller builds these from metrics history (engine
    deployments) or its own router-report ring (plain deployments)."""
    ts: float
    utilization: float
    waiting: float = 0.0


@dataclasses.dataclass(frozen=True)
class Decision:
    target: int                     # desired replica count (serving)
    reason: str
    # replicas to retire when target < current, best victims first
    victims: Tuple[str, ...] = ()

    @property
    def direction(self) -> int:
        return 0 if not self.reason else (
            1 if self.reason.startswith("up") else
            -1 if self.reason.startswith("down") else 0)


def _cfg(auto: Dict, key: str, default: float) -> float:
    v = auto.get(key)
    return default if v is None else float(v)


def weighted_capacity(replicas: Sequence[ReplicaView],
                      suspect_weight: float) -> float:
    return sum((r.capacity * (suspect_weight if r.suspect else 1.0))
               for r in replicas if not r.retiring)


def fleet_sample(ts: float, replicas: Sequence[ReplicaView],
                 suspect_weight: float) -> FleetSample:
    """Fold per-replica views into one trend point."""
    cap = weighted_capacity(replicas, suspect_weight)
    occ = sum(r.occupied for r in replicas if not r.retiring)
    wait = sum(r.waiting for r in replicas if not r.retiring)
    return FleetSample(ts=ts, utilization=(occ / cap) if cap > 0 else
                       (1.0 if (occ or wait) else 0.0), waiting=wait)


def pick_victims(replicas: Sequence[ReplicaView], n: int) -> Tuple[str, ...]:
    """Scale-down victims: suspect replicas first (their capacity is
    already down-weighted away), then least-loaded — retiring the
    emptiest replica migrates the fewest live sessions."""
    pool = [r for r in replicas if not r.retiring]
    pool.sort(key=lambda r: (not r.suspect, r.occupied + r.waiting))
    return tuple(r.replica_id for r in pool[:max(0, n)])


def decide(auto: Dict, replicas: Sequence[ReplicaView],
           series: Sequence[FleetSample], now: float,
           last_up: float = 0.0, last_down: float = 0.0) -> Decision:
    """Pure autoscale decision.

    ``auto`` is the deployment's autoscaling_config mapping (missing
    keys fall back to :class:`AutoscalingConfig` defaults, so dict
    configs from YAML deploys work unchanged); ``replicas`` the current
    fleet view; ``series`` the time-ordered trend samples (the newest
    matter; empty series = no signal, hold); ``now``/``last_up``/
    ``last_down`` are explicit clocks so cooldown is testable."""
    cur = sum(1 for r in replicas if not r.retiring)
    lo = int(_cfg(auto, "min_replicas", 1))
    hi = int(_cfg(auto, "max_replicas", 4))
    if cur < lo:
        return Decision(lo, "up:below-min")
    window_s = _cfg(auto, "trend_window_s", 10.0)
    occ_high = _cfg(auto, "occupancy_high", 0.8)
    occ_low = _cfg(auto, "occupancy_low", 0.3)
    target_occ = max(0.05, _cfg(auto, "target_occupancy", 0.6))
    suspect_w = _cfg(auto, "suspect_weight", 0.25)
    win = [s for s in series if s.ts >= now - window_s]
    if not win:
        return Decision(cur, "")
    latest = win[-1]
    # recent = newest half of the window's SAMPLES: the trend's "where
    # is it heading" read (a single hot sample does not scale the
    # fleet, a sustained climb does; count-based halving stays correct
    # whatever the tick cadence)
    half = win[len(win) // 2:] or [latest]
    recent_u = sum(s.utilization for s in half) / len(half)
    recent_wait = sum(s.waiting for s in half) / len(half)
    avg_u = sum(s.utilization for s in win) / len(win)
    avg_wait = sum(s.waiting for s in win) / len(win)

    cap_unit = max(0.05, (weighted_capacity(replicas, suspect_w) / cur)
                   if cur else _cfg(auto, "target_num_ongoing_requests_"
                                    "per_replica", 2.0))
    demand = latest.utilization * weighted_capacity(replicas, suspect_w) \
        + latest.waiting

    # waiting depth only counts as pressure when slots are actually
    # busy — one session transiting the admission queue while the
    # fleet has free capacity is latency, not load, and scaling on it
    # flaps the fleet on every trickle
    wait_pressure = recent_wait >= 1.0 and recent_u >= target_occ
    if (recent_u >= occ_high or wait_pressure) and cur < hi:
        if now - last_up < _cfg(auto, "upscale_delay_s", 0.0):
            return Decision(cur, "")          # cooldown: hold
        desired = int(math.ceil(demand / (target_occ * cap_unit)))
        desired = min(hi, max(desired, cur + 1))
        return Decision(desired, "up:occupancy-trend")

    if recent_u < occ_high and avg_u <= occ_low and avg_wait < 0.5 \
            and cur > lo:
        if now - last_down < _cfg(auto, "downscale_delay_s", 2.0):
            return Decision(cur, "")
        desired = int(math.ceil(demand / (target_occ * cap_unit))) \
            if demand > 0 else lo
        desired = max(lo, min(desired, cur - 1))
        if desired >= cur:
            return Decision(cur, "")
        return Decision(desired, "down:idle",
                        victims=pick_victims(replicas, cur - desired))

    return Decision(cur, "")   # hysteresis band: hold steady
