"""Replica actor: hosts one copy of the user callable.

Capability mirror of the reference's `RayServeReplica`
(`serve/_private/replica.py:250,494`) — wraps the deployment's
class/function, counts in-flight queries, supports `reconfigure`
(user_config hot update) and async handlers.  Runs with
``max_concurrency > 1`` so `@serve.batch` queues can fill.
"""

from __future__ import annotations

import asyncio
import dataclasses
import inspect
import threading
import time
from typing import Any, Dict, Optional


@dataclasses.dataclass(frozen=True)
class ReplicaContext:
    """What serve.get_replica_context() returns inside a replica
    (reference: serve/context.py ReplicaContext)."""
    deployment: str
    replica_tag: str


#: set by ServeReplica.__init__ in the replica's worker process
_replica_context: Optional[ReplicaContext] = None


def get_replica_context() -> ReplicaContext:
    if _replica_context is None:
        raise RuntimeError(
            "get_replica_context() may only be called inside a Serve "
            "replica (deployment __init__ or request handler)")
    return _replica_context


class ServeReplica:
    def __init__(self, deployment_name: str, replica_id: str,
                 callable_blob: bytes, init_args: tuple,
                 init_kwargs: Dict[str, Any], user_config: Any):
        from ..core.serialization import loads_function
        self.deployment_name = deployment_name
        self.replica_id = replica_id
        # replica context (reference: serve.get_replica_context()) —
        # set BEFORE user __init__ runs so constructors can read it
        global _replica_context
        _replica_context = ReplicaContext(deployment_name, replica_id)
        t0 = time.perf_counter()
        fc = loads_function(callable_blob)
        if inspect.isclass(fc):
            self._callable = fc(*init_args, **init_kwargs)
            self._is_function = False
        else:
            self._callable = fc
            self._is_function = True
        self._num_ongoing = 0
        self._lock = threading.Lock()
        self._total = 0
        if user_config is not None:
            self.reconfigure(user_config)
        # cold-start attribution (serve_breakdown's `cold_start`
        # phase): replicas construct lazily, so worker acquisition plus
        # the user constructor — model init, first jit compiles — sit
        # inside the first request's client-measured TTFT.  Without
        # this one-shot push that time is unattributable and the
        # coverage bar reads a cold cluster as an instrumentation gap.
        # The constructor runs AS an actor task, so its spec's
        # submit_time extends the phase back to the controller-side
        # creation submit (covering scheduling/spawn wait too).
        dt = time.perf_counter() - t0
        try:
            from ..core.worker_runtime import (current_task_spec,
                                               current_worker_runtime)
            spec = current_task_spec()
            if spec is not None and getattr(spec, "submit_time", 0):
                dt = max(dt, time.time() - spec.submit_time)
            rt = current_worker_runtime()
            if rt is not None and rt._loop is not None:
                asyncio.run_coroutine_threadsafe(
                    rt.nodelet.notify("serve_metrics", {
                        "deployment": deployment_name,
                        "replica": replica_id,
                        "phase_totals": {"cold_start": round(dt, 6)}}),
                    rt._loop)
        except Exception:
            pass

    def reconfigure(self, user_config: Any) -> bool:
        target = self._callable
        if not self._is_function and hasattr(target, "reconfigure"):
            target.reconfigure(user_config)
        return True

    def _trace_args(self) -> Dict[str, Any]:
        """Span attribution for the request being handled: the replica's
        identity plus the actor-task spec's trace id, so serve spans
        join the same timeline as the task-lifecycle spans."""
        tr = {"deployment": self.deployment_name,
              "replica": self.replica_id}
        from ..core.worker_runtime import current_task_spec
        spec = current_task_spec()
        if spec is not None:
            tr["task_id"] = spec.task_id.hex()
            tr["trace"] = spec.trace_id
        return tr

    def _chaos_site(self, site: str) -> None:
        """Chaos-layer hook for serve scenarios: the replica dies
        mid-request (`crash`), fails the request (`error`), or stalls
        (`latency`) — the router/handle retry path must keep these
        invisible to callers."""
        from ..util import fault_injection as fi
        if fi.ACTIVE is None:
            return
        act = fi.ACTIVE.point(site, self.deployment_name)
        if act is None:
            return
        if act["action"] == "crash":
            import asyncio
            import os

            from ..core.worker_runtime import current_worker_runtime
            rt = current_worker_runtime()
            if act["once"]:
                # claim through the controller (exactly one replica
                # cluster-wide takes the hit); runs on an executor
                # thread, so hop onto the worker's event loop
                claimed = fi.local_claim(act["rule_id"])
                if rt is not None and rt._loop is not None:
                    try:
                        claimed = asyncio.run_coroutine_threadsafe(
                            rt._chaos_claim(act["rule_id"]),
                            rt._loop).result(5)
                    except Exception:
                        pass
                if not claimed:
                    return
            if rt is not None and rt._loop is not None:
                try:
                    asyncio.run_coroutine_threadsafe(
                        rt.nodelet.notify(
                            "chaos_injected",
                            {"site": site, "action": "crash"}),
                        rt._loop).result(2)
                except Exception:
                    pass
            os._exit(fi.CRASH_EXIT_CODE)
        if act["action"] in ("delay", "latency"):
            time.sleep(max(0.0, act["delay_s"]))
        elif act["action"] in ("error", "fail"):
            raise RuntimeError(
                f"chaos: injected {site} failure in "
                f"{self.deployment_name}/{self.replica_id}")

    def handle_request(self, args: tuple, kwargs: Dict[str, Any],
                       method: Optional[str] = None) -> Any:
        from ..core.worker_runtime import current_task_spec
        from ..util import tracing
        self._chaos_site("serve.request")
        tr = self._trace_args()
        spec = current_task_spec()
        now = time.time()
        if spec is not None and spec.submit_time:
            # router assign -> replica start: the request's queue leg
            tracing.record_span(f"serve_queue::{self.deployment_name}",
                                "serve", spec.submit_time, now, **tr)
        with self._lock:
            self._num_ongoing += 1
            self._total += 1
        try:
            target = self._callable
            if not self._is_function and method:
                target = getattr(target, method)
            elif not self._is_function:
                target = target.__call__
            result = target(*args, **kwargs)
            if inspect.iscoroutine(result):
                result = asyncio.run(result)
            return result
        finally:
            tracing.record_span(f"serve_exec::{self.deployment_name}",
                                "serve", now, time.time(), **tr)
            with self._lock:
                self._num_ongoing -= 1

    def metrics(self) -> Dict[str, Any]:
        with self._lock:
            out = {"replica_id": self.replica_id,
                   "num_ongoing": self._num_ongoing,
                   "total": self._total}
        # decode-session deployments expose their continuous-batching
        # engine's occupancy/queue counters (the callable convention:
        # any `engine_stats()` method merges into replica metrics, so
        # autoscalers/dashboards see slot pressure, not just RPC counts)
        target = self._callable
        if not self._is_function and hasattr(target, "engine_stats"):
            try:
                out["engine"] = target.engine_stats()
            except Exception:
                pass
        return out

    def health_check(self) -> bool:
        self._chaos_site("serve.health_check")
        target = self._callable
        if not self._is_function and hasattr(target, "check_health"):
            target.check_health()
        return True

    # ---------------------------------------------- decode-session drain
    def _my_engines(self):
        """Continuous-batching engines living in THIS replica's process
        (decode_session registers every engine in a process-wide set;
        filter by replica tag in case a worker ever hosts several)."""
        from .decode_session import _ENGINES
        return [eng for eng in list(_ENGINES)
                if getattr(eng, "_tag", None) in (self.replica_id,
                                                  "local")]

    def prepare_drain(self) -> int:
        """Replica is about to be stopped (node drain evacuation): put
        every decode engine into drain mode so live sessions hand
        themselves off — new starts shed with the typed 503, blocked
        `next_chunk` waits wake and deliver their buffered tokens with
        the ``migrating`` flag, and the proxy-side failover client
        re-admits each session on a healthy replica.  Returns the
        number of sessions awaiting handoff."""
        return sum(eng.begin_drain() for eng in self._my_engines())

    def drain_status(self) -> Dict[str, Any]:
        """Live-session count the controller polls before stopping a
        draining replica — zero means every stream has migrated (or
        ended) and the replica can die without dropping a session."""
        return {"live_sessions": sum(eng.live_sessions()
                                     for eng in self._my_engines())}
