"""Replica actor: hosts one copy of the user callable.

Capability mirror of the reference's `RayServeReplica`
(`serve/_private/replica.py:250,494`) — wraps the deployment's
class/function, counts in-flight queries, supports `reconfigure`
(user_config hot update) and async handlers.  Runs with
``max_concurrency > 1`` so `@serve.batch` queues can fill.
"""

from __future__ import annotations

import asyncio
import dataclasses
import inspect
import threading
import time
from typing import Any, Dict, Optional


@dataclasses.dataclass(frozen=True)
class ReplicaContext:
    """What serve.get_replica_context() returns inside a replica
    (reference: serve/context.py ReplicaContext)."""
    deployment: str
    replica_tag: str


#: set by ServeReplica.__init__ in the replica's worker process
_replica_context: Optional[ReplicaContext] = None


def get_replica_context() -> ReplicaContext:
    if _replica_context is None:
        raise RuntimeError(
            "get_replica_context() may only be called inside a Serve "
            "replica (deployment __init__ or request handler)")
    return _replica_context


class ServeReplica:
    def __init__(self, deployment_name: str, replica_id: str,
                 callable_blob: bytes, init_args: tuple,
                 init_kwargs: Dict[str, Any], user_config: Any):
        from ..core.serialization import loads_function
        self.deployment_name = deployment_name
        self.replica_id = replica_id
        # replica context (reference: serve.get_replica_context()) —
        # set BEFORE user __init__ runs so constructors can read it
        global _replica_context
        _replica_context = ReplicaContext(deployment_name, replica_id)
        fc = loads_function(callable_blob)
        if inspect.isclass(fc):
            self._callable = fc(*init_args, **init_kwargs)
            self._is_function = False
        else:
            self._callable = fc
            self._is_function = True
        self._num_ongoing = 0
        self._lock = threading.Lock()
        self._total = 0
        if user_config is not None:
            self.reconfigure(user_config)

    def reconfigure(self, user_config: Any) -> bool:
        target = self._callable
        if not self._is_function and hasattr(target, "reconfigure"):
            target.reconfigure(user_config)
        return True

    def _trace_args(self) -> Dict[str, Any]:
        """Span attribution for the request being handled: the replica's
        identity plus the actor-task spec's trace id, so serve spans
        join the same timeline as the task-lifecycle spans."""
        tr = {"deployment": self.deployment_name,
              "replica": self.replica_id}
        from ..core.worker_runtime import current_task_spec
        spec = current_task_spec()
        if spec is not None:
            tr["task_id"] = spec.task_id.hex()
            tr["trace"] = spec.trace_id
        return tr

    def handle_request(self, args: tuple, kwargs: Dict[str, Any],
                       method: Optional[str] = None) -> Any:
        from ..core.worker_runtime import current_task_spec
        from ..util import tracing
        tr = self._trace_args()
        spec = current_task_spec()
        now = time.time()
        if spec is not None and spec.submit_time:
            # router assign -> replica start: the request's queue leg
            tracing.record_span(f"serve_queue::{self.deployment_name}",
                                "serve", spec.submit_time, now, **tr)
        with self._lock:
            self._num_ongoing += 1
            self._total += 1
        try:
            target = self._callable
            if not self._is_function and method:
                target = getattr(target, method)
            elif not self._is_function:
                target = target.__call__
            result = target(*args, **kwargs)
            if inspect.iscoroutine(result):
                result = asyncio.run(result)
            return result
        finally:
            tracing.record_span(f"serve_exec::{self.deployment_name}",
                                "serve", now, time.time(), **tr)
            with self._lock:
                self._num_ongoing -= 1

    def metrics(self) -> Dict[str, Any]:
        with self._lock:
            return {"replica_id": self.replica_id,
                    "num_ongoing": self._num_ongoing,
                    "total": self._total}

    def health_check(self) -> bool:
        target = self._callable
        if not self._is_function and hasattr(target, "check_health"):
            target.check_health()
        return True
