"""ServeHandle: Python-side calls into a deployment (reference:
`serve/handle.py` RayServeHandle / DeploymentHandle)."""

from __future__ import annotations

from typing import Any, Optional

from .. import api


class _MethodCaller:
    def __init__(self, handle: "ServeHandle", method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs):
        return self._handle._call(args, kwargs, self._method)


class ServeHandle:
    def __init__(self, router, deployment_name: str):
        self._router = router
        self._name = deployment_name

    def remote(self, *args, **kwargs):
        """Returns an ObjectRef with the response."""
        return self._call(args, kwargs, None)

    def _call(self, args, kwargs, method: Optional[str]):
        ref, replica_id = self._router.assign_request(
            self._name, args, kwargs, method)
        # completion accounting piggybacks on result retrieval
        return _TrackedRef(ref, self._router, self._name, replica_id,
                           args, kwargs, method)

    def __getattr__(self, item: str) -> _MethodCaller:
        if item.startswith("_"):
            raise AttributeError(item)
        return _MethodCaller(self, item)


def is_replica_down_error(exc: BaseException) -> bool:
    """A failure that blames the REPLICA, not the request: killed mid-
    call (redeploy/scale-down race) or its worker died.  Typed — never
    inferred from message text, which would re-run non-idempotent user
    requests whose own errors merely mention 'died'."""
    from ..exceptions import ActorDiedError, WorkerCrashedError
    return isinstance(exc, (ActorDiedError, WorkerCrashedError))


def _shed_error(exc: BaseException):
    """The typed 503 signal, whether raised router-side (zero live
    replicas, sticky owner gone) or replica-side (decode-engine
    admission backpressure, draining engine) — the latter arrives
    wrapped in the remote TaskError."""
    from ..exceptions import ReplicaUnavailableError, TaskError
    if isinstance(exc, ReplicaUnavailableError):
        return exc
    if isinstance(exc, TaskError) and isinstance(
            getattr(exc, "cause", None), ReplicaUnavailableError):
        return exc.cause
    return None


def call_with_retry(router, name: str, args, kwargs,
                    method: Optional[str] = None,
                    timeout_s: float = 60.0, attempts: int = 3,
                    sticky_replica_id: Optional[str] = None,
                    prefix_tokens=None) -> Any:
    """Assign + get with replica-failure retry under ONE deadline (the
    reference router's handling of dead replicas).  A request that
    raced a replica teardown re-routes to a live replica after a table
    refresh; user errors propagate untouched on the first attempt.
    Retry attempts are spaced by capped full-jitter backoff so a burst
    of failed requests doesn't hammer the table refresh and the
    surviving replicas in lockstep.

    A typed shed (``ReplicaUnavailableError`` — zero live replicas, or
    replica-side admission backpressure) carries a server-sent
    ``Retry-After`` hint; instead of the fixed retry cadence, attempts
    after a shed are spaced by full-jitter delays sampled from that
    hint (``uniform(0, retry_after * 2**n)``, capped) — the server said
    when to come back, and jitter keeps a burst of shed clients from
    returning in lockstep.  After ``attempts`` sheds the error
    propagates (the HTTP proxy maps it to 503 + Retry-After).

    A ``sticky_replica_id`` request (decode-session ops: the KV cache
    lives on one replica) never re-routes: the replica dying took the
    session with it, so the failure propagates for the caller to
    surface (the SSE lane's failover client re-admits the session on a
    healthy replica via teacher-forced replay)."""
    import time as _time

    from ..core.config import GlobalConfig
    from ..util.backoff import ExponentialBackoff
    deadline = _time.monotonic() + timeout_s
    bo = ExponentialBackoff(base=GlobalConfig.serve_backoff_base_s,
                            cap=GlobalConfig.serve_backoff_cap_s)
    shed_bo = None   # built lazily from the first Retry-After hint

    def _shed_wait(shed) -> bool:
        """Sleep a full-jitter delay honoring the shed's Retry-After;
        False when the deadline can't absorb another wait."""
        nonlocal shed_bo
        remaining = deadline - _time.monotonic()
        if remaining <= 0:
            return False
        if shed_bo is None:
            ra = max(float(getattr(shed, "retry_after_s", 1.0) or 1.0),
                     1e-3)
            shed_bo = ExponentialBackoff(base=ra, cap=4.0 * ra)
        _time.sleep(min(shed_bo.next_delay(), remaining))
        return True

    for attempt in range(attempts):
        budget = max(0.1, deadline - _time.monotonic())
        try:
            # prefix_tokens only when set: scripted fake routers in
            # tests predate the affinity parameter
            extra = ({"prefix_tokens": prefix_tokens}
                     if prefix_tokens is not None else {})
            ref, rid = router.assign_request(
                name, args, kwargs, method, timeout_s=budget,
                sticky_replica_id=sticky_replica_id, **extra)
        except Exception as e:
            shed = _shed_error(e)
            if shed is None or sticky_replica_id is not None \
                    or attempt == attempts - 1 or not _shed_wait(shed):
                raise
            continue
        try:
            return api.get(ref,
                           timeout=max(0.1,
                                       deadline - _time.monotonic()))
        except Exception as e:
            shed = _shed_error(e)
            if shed is not None and sticky_replica_id is None \
                    and attempt < attempts - 1 and _shed_wait(shed):
                continue
            if attempt == attempts - 1 or not is_replica_down_error(e) \
                    or sticky_replica_id is not None \
                    or _time.monotonic() >= deadline:
                raise
            router._refresh(force=True)
            _time.sleep(min(bo.next_delay(),
                            max(0.0, deadline - _time.monotonic())))
        finally:
            router.complete(name, rid)


class _TrackedRef:
    """ObjectRef wrapper that releases the router's in-flight slot when the
    result is fetched."""

    def __init__(self, ref, router, name, replica_id,
                 args=(), kwargs=None, method=None):
        self._ref = ref
        self._router = router
        self._name = name
        self._replica_id = replica_id
        self._args = args
        self._kwargs = kwargs or {}
        self._method = method
        self._done = False

    def result(self, timeout_s: float = 60.0) -> Any:
        import time as _time
        t0 = _time.monotonic()
        try:
            try:
                return api.get(self._ref, timeout=timeout_s)
            finally:
                self._release()
        except Exception as e:
            remaining = timeout_s - (_time.monotonic() - t0)
            if not is_replica_down_error(e) or remaining <= 0:
                raise
            self._router._refresh(force=True)
            return call_with_retry(self._router, self._name, self._args,
                                   self._kwargs, self._method,
                                   timeout_s=remaining, attempts=2)

    def _release(self):
        if not self._done:
            self._done = True
            self._router.complete(self._name, self._replica_id)

    @property
    def ref(self):
        return self._ref
