"""ServeHandle: Python-side calls into a deployment (reference:
`serve/handle.py` RayServeHandle / DeploymentHandle)."""

from __future__ import annotations

from typing import Any, Optional

from .. import api


class _MethodCaller:
    def __init__(self, handle: "ServeHandle", method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs):
        return self._handle._call(args, kwargs, self._method)


class ServeHandle:
    def __init__(self, router, deployment_name: str):
        self._router = router
        self._name = deployment_name

    def remote(self, *args, **kwargs):
        """Returns an ObjectRef with the response."""
        return self._call(args, kwargs, None)

    def _call(self, args, kwargs, method: Optional[str]):
        ref, replica_id = self._router.assign_request(
            self._name, args, kwargs, method)
        # completion accounting piggybacks on result retrieval
        return _TrackedRef(ref, self._router, self._name, replica_id)

    def __getattr__(self, item: str) -> _MethodCaller:
        if item.startswith("_"):
            raise AttributeError(item)
        return _MethodCaller(self, item)


class _TrackedRef:
    """ObjectRef wrapper that releases the router's in-flight slot when the
    result is fetched."""

    def __init__(self, ref, router, name, replica_id):
        self._ref = ref
        self._router = router
        self._name = name
        self._replica_id = replica_id
        self._done = False

    def result(self, timeout_s: float = 60.0) -> Any:
        try:
            return api.get(self._ref, timeout=timeout_s)
        finally:
            self._release()

    def _release(self):
        if not self._done:
            self._done = True
            self._router.complete(self._name, self._replica_id)

    @property
    def ref(self):
        return self._ref
