"""Usage stats: local, opt-in session usage reports.

Capability mirror of the reference's usage-stats subsystem
(/root/reference/python/ray/_private/usage/usage_lib.py + the dashboard
usage module) with the telemetry inverted for this environment: nothing
ever leaves the machine — when ``usage_stats_enabled`` is on, a JSON
usage report (cluster shape, feature-use counters, task/actor volumes)
is written under the session dir at shutdown for the operator's own
fleet accounting.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict

_feature_uses: Dict[str, int] = {}


def record_feature(name: str) -> None:
    """Libraries call this on first use (train/tune/serve/data/rl/...)."""
    _feature_uses[name] = _feature_uses.get(name, 0) + 1


def collect() -> Dict[str, Any]:
    from . import state
    from .core.config import GlobalConfig
    report: Dict[str, Any] = {
        "ts": time.time(),
        "version": __import__("ray_tpu").__version__,
        "features": dict(_feature_uses),
    }
    try:
        report["cluster"] = state.cluster_summary()
        report["nodes"] = [
            {"resources": n.get("total"), "alive": n.get("alive")}
            for n in state.list_nodes()]
    except Exception:
        pass
    report["config_overrides"] = {
        k: v for k, v in GlobalConfig.snapshot().items()
        if os.environ.get(f"RAY_TPU_{k.upper()}") is not None}
    return report


def write_report(session_dir: str) -> str:
    path = os.path.join(session_dir, "usage_report.json")
    with open(path, "w") as f:
        json.dump(collect(), f, indent=2, default=str)
    return path


def maybe_write_report(session_dir: str) -> None:
    from .core.config import GlobalConfig
    if getattr(GlobalConfig, "usage_stats_enabled", False):
        try:
            write_report(session_dir)
        except Exception:
            pass
