"""Core-op microbenchmarks.

Capability mirror of the reference's `python/ray/_private/ray_perf.py:93-150`
(`ray microbenchmark` CLI): per-op throughput for tasks, actor calls, puts
and gets on a live cluster.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List

import numpy as np


def _rate(fn: Callable[[], int], min_time: float = 1.0) -> float:
    """ops/s: run batches until min_time elapsed."""
    fn()  # warmup
    n = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < min_time:
        n += fn()
    return n / (time.perf_counter() - t0)


def run_microbenchmarks(min_time: float = 1.0,
                        include_serve: bool = False) -> Dict[str, float]:
    import ray_tpu

    @ray_tpu.remote
    def noop():
        return None

    @ray_tpu.remote
    class Actor:
        def noop(self):
            return None

    results: Dict[str, float] = {}

    def tasks_batch():
        ray_tpu.get([noop.remote() for _ in range(100)], timeout=60.0)
        return 100

    results["tasks_per_s"] = _rate(tasks_batch, min_time)

    actor = Actor.remote()

    def actor_batch():
        ray_tpu.get([actor.noop.remote() for _ in range(100)],
                    timeout=60.0)
        return 100

    results["actor_calls_per_s"] = _rate(actor_batch, min_time)

    small = b"x" * 1024

    def put_batch():
        [ray_tpu.put(small) for _ in range(100)]
        return 100

    results["put_1kb_per_s"] = _rate(put_batch, min_time)

    big = np.zeros(8 * 1024 * 1024, dtype=np.uint8)  # 8 MiB

    def put_big():
        ref = ray_tpu.put(big)
        ray_tpu.get(ref, timeout=60.0)
        return 1

    rt = _rate(put_big, min_time)
    results["put_get_roundtrip_GBps"] = rt * big.nbytes / 1e9

    def get_many():
        refs = [ray_tpu.put(small) for _ in range(100)]
        ray_tpu.get(refs, timeout=60.0)
        return 100

    results["get_1kb_per_s"] = _rate(get_many, min_time)

    if not include_serve:
        # serve boots a controller + proxy + replica into the CALLER'S
        # cluster — opt-in only (the CLI passes it; library callers with
        # small clusters keep the core numbers cheap)
        return results
    # Serve overhead (BASELINE row: the reference documents ~1-2 ms added
    # latency, doc/source/serve/performance.md:19): time a no-op
    # deployment end to end through handle + router + replica.
    deployed = False
    try:
        from ray_tpu import serve

        @serve.deployment(max_concurrent_queries=64)
        def _bench_noop(x=None):
            return x

        handle = serve.run(_bench_noop, name="_bench_noop")
        deployed = True
        handle.remote(1).result(timeout_s=60.0)  # warm the path

        def serve_batch():
            futs = [handle.remote(i) for i in range(20)]
            for f in futs:
                f.result(timeout_s=60.0)
            return 20

        qps = _rate(serve_batch, min_time)
        results["serve_noop_qps"] = qps
        # sequential round trip = the added-latency figure
        t0 = time.perf_counter()
        n = 50
        for i in range(n):
            handle.remote(i).result(timeout_s=60.0)
        results["serve_latency_ms"] = (
            (time.perf_counter() - t0) / n * 1000)
    except Exception:  # pragma: no cover - serve-less contexts
        import sys
        import traceback
        print("microbenchmark: serve section skipped:", file=sys.stderr)
        traceback.print_exc(file=sys.stderr)
    finally:
        if deployed:
            try:  # never leave the bench deployment in the caller's cluster
                serve.delete("_bench_noop")
            except Exception:
                pass
    return results
