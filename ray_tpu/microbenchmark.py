"""Core-op microbenchmarks.

Capability mirror of the reference's `python/ray/_private/ray_perf.py:93-150`
(`ray microbenchmark` CLI): per-op throughput for tasks, actor calls, puts
and gets on a live cluster.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List

import numpy as np


def _rate(fn: Callable[[], int], min_time: float = 1.0) -> float:
    """ops/s: run batches until min_time elapsed."""
    fn()  # warmup
    n = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < min_time:
        n += fn()
    return n / (time.perf_counter() - t0)


def run_microbenchmarks(min_time: float = 1.0) -> Dict[str, float]:
    import ray_tpu

    @ray_tpu.remote
    def noop():
        return None

    @ray_tpu.remote
    class Actor:
        def noop(self):
            return None

    results: Dict[str, float] = {}

    def tasks_batch():
        ray_tpu.get([noop.remote() for _ in range(100)], timeout=60.0)
        return 100

    results["tasks_per_s"] = _rate(tasks_batch, min_time)

    actor = Actor.remote()

    def actor_batch():
        ray_tpu.get([actor.noop.remote() for _ in range(100)],
                    timeout=60.0)
        return 100

    results["actor_calls_per_s"] = _rate(actor_batch, min_time)

    small = b"x" * 1024

    def put_batch():
        [ray_tpu.put(small) for _ in range(100)]
        return 100

    results["put_1kb_per_s"] = _rate(put_batch, min_time)

    big = np.zeros(8 * 1024 * 1024, dtype=np.uint8)  # 8 MiB

    def put_big():
        ref = ray_tpu.put(big)
        ray_tpu.get(ref, timeout=60.0)
        return 1

    rt = _rate(put_big, min_time)
    results["put_get_roundtrip_GBps"] = rt * big.nbytes / 1e9

    def get_many():
        refs = [ray_tpu.put(small) for _ in range(100)]
        ray_tpu.get(refs, timeout=60.0)
        return 100

    results["get_1kb_per_s"] = _rate(get_many, min_time)
    return results
