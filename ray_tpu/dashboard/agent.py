"""Per-node dashboard agent: OS-level stats + log serving off the
nodelet's critical path.

Capability mirror of the reference's per-node agent
(/root/reference/dashboard/agent.py:1 — a process the raylet launches
next to itself; reporter/log modules sample the NODE while the head
aggregates).  The split matters for the same reason as there: stats
sampling and log tailing are IO the scheduler loop must not pay for,
and a crashed agent must not take worker scheduling down with it.

TPU-first shape: the agent is ~200 LoC riding the framework's own RPC
plane and controller KV (namespace ``dashboard``, key
``agent:<node_id>`` → address, heartbeat-refreshed) instead of the
reference's gRPC + Redis; the head discovers agents through the KV and
falls back to the nodelet scrape path when an agent is dead — logs and
stats stay served either way.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional

AGENT_KV_NS = "dashboard"
AGENT_KV_PREFIX = "agent:"


def _read_proc_stat() -> Dict[str, float]:
    with open("/proc/stat") as f:
        fields = f.readline().split()[1:8]
    vals = [float(x) for x in fields]
    idle = vals[3] + vals[4]
    return {"total": sum(vals), "idle": idle}


def _meminfo() -> Dict[str, float]:
    out = {}
    with open("/proc/meminfo") as f:
        for line in f:
            k, v = line.split(":", 1)
            if k in ("MemTotal", "MemAvailable"):
                out[k] = float(v.strip().split()[0]) * 1024
    return out


class DashboardAgent:
    """Samples node stats, serves logs, heartbeats into controller KV."""

    def __init__(self, *, node_id: str, session_dir: str,
                 controller_addr: str, nodelet_addr: str = "",
                 host: str = "127.0.0.1", port: int = 0,
                 heartbeat_s: float = 2.0):
        from ..core import rpc
        self.node_id = node_id
        self.session_dir = session_dir
        self.nodelet_addr = nodelet_addr
        self.heartbeat_s = heartbeat_s
        self._cpu_prev = _read_proc_stat()
        self._cpu_pct = 0.0
        self._lt = rpc.EventLoopThread("dashboard-agent")
        self.server = rpc.RpcServer(host, port)
        for name in ("agent_stats", "list_logs", "tail_log"):
            fn = getattr(self, "_h_" + name)

            async def handler(conn, data, _fn=fn):
                return _fn(data or {})
            self.server.register(name, handler)
        self._lt.run(self.server.start())
        self.address = f"{self.server.host}:{self.server.port}"
        self._controller = rpc.BlockingClient.connect_ha(
            self._lt, controller_addr)
        self._stop = threading.Event()
        self._hb_thread = threading.Thread(target=self._heartbeat_loop,
                                           daemon=True,
                                           name="agent-heartbeat")
        self._hb_thread.start()

    # -- registration --------------------------------------------------------
    def _heartbeat_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._controller.call("kv_put", {
                    "ns": AGENT_KV_NS,
                    "key": AGENT_KV_PREFIX + self.node_id,
                    "value": json.dumps({
                        "addr": self.address, "pid": os.getpid(),
                        "ts": time.time(),
                        "heartbeat_s": self.heartbeat_s}),
                    # liveness beat, not durable state: no WAL record
                    "persist": False,
                }, timeout=5.0)
            except Exception:
                pass    # controller restarting: keep trying
            self._stop.wait(self.heartbeat_s)

    def stop(self) -> None:
        self._stop.set()
        try:
            self._controller.call("kv_del", {
                "ns": AGENT_KV_NS,
                "key": AGENT_KV_PREFIX + self.node_id}, timeout=2.0)
        except Exception:
            pass
        try:
            self._controller.close()
            self._lt.run(self.server.stop())
        finally:
            self._lt.stop()

    # -- handlers ------------------------------------------------------------
    def _h_agent_stats(self, data) -> Dict[str, Any]:
        """Node-level OS stats sampled from /proc (reference:
        dashboard/modules/reporter/reporter_agent.py's psutil set)."""
        cur = _read_proc_stat()
        dt = cur["total"] - self._cpu_prev["total"]
        didle = cur["idle"] - self._cpu_prev["idle"]
        if dt > 0:
            self._cpu_pct = max(0.0, min(100.0,
                                         100.0 * (1.0 - didle / dt)))
        self._cpu_prev = cur
        mem = _meminfo()
        try:
            load1, load5, load15 = os.getloadavg()
        except OSError:
            load1 = load5 = load15 = 0.0
        return {
            "node_id": self.node_id,
            "agent_addr": self.address,
            "agent_pid": os.getpid(),
            "cpu_percent": round(self._cpu_pct, 1),
            "mem_total": mem.get("MemTotal", 0.0),
            "mem_available": mem.get("MemAvailable", 0.0),
            "load_avg": [load1, load5, load15],
            "log_files": self._log_files(),
        }

    def _log_dir(self) -> str:
        return os.path.join(self.session_dir, "logs")

    def _log_files(self) -> list:
        try:
            return sorted(os.listdir(self._log_dir()))
        except OSError:
            return []

    def _h_list_logs(self, data) -> Dict[str, Any]:
        return {"files": self._log_files()}

    def _h_tail_log(self, data) -> Dict[str, Any]:
        name = data.get("name", "")
        if "/" in name or ".." in name:
            return {"error": "bad log name"}
        path = os.path.join(self._log_dir(), name)
        try:
            size = os.path.getsize(path)
            nbytes = int(data.get("bytes", 65536))
            with open(path, "rb") as f:
                f.seek(max(0, size - nbytes))
                return {"data": f.read()}
        except OSError as e:
            return {"error": str(e)}


def main() -> None:
    import argparse
    import signal
    parser = argparse.ArgumentParser()
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--controller", required=True)
    parser.add_argument("--nodelet-addr", default="")
    args = parser.parse_args()
    agent = DashboardAgent(node_id=args.node_id,
                           session_dir=args.session_dir,
                           controller_addr=args.controller,
                           nodelet_addr=args.nodelet_addr)
    done = threading.Event()
    # the nodelet stops us with SIGTERM: deregister from the KV so the
    # head doesn't keep dialing a dead address until the TTL lapses
    signal.signal(signal.SIGTERM, lambda *_: done.set())
    signal.signal(signal.SIGINT, lambda *_: done.set())
    done.wait()
    agent.stop()


if __name__ == "__main__":
    main()
