"""Dashboard: REST head exposing cluster state.

Capability mirror of the reference's `dashboard/head.py` + modules
(`dashboard/modules/{node,actor,job,reporter,metrics}`): an aiohttp app
serving the state API, job submission, and Prometheus metrics over HTTP.
The TS frontend is out of scope; the API surface matches what it consumes.
"""

from .head import DashboardHead, start_dashboard  # noqa: F401
