"""The dashboard head server (reference: `dashboard/head.py` aiohttp app;
job endpoints mirror `dashboard/modules/job/job_head.py`)."""

from __future__ import annotations

import asyncio
import json
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Optional


class DashboardHead:
    def __init__(self, host: str = "127.0.0.1", port: int = 8265):
        self._host = host
        self._port = port
        self._pool = ThreadPoolExecutor(max_workers=8)
        self._ready = threading.Event()
        self._error: Optional[str] = None
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        self._ready.wait(timeout=15.0)
        if self._error:
            raise RuntimeError(self._error)

    @property
    def address(self) -> str:
        return f"http://{self._host}:{self._port}"

    def _serve(self) -> None:
        from aiohttp import web

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)

        def blocking(fn):
            async def handler(request):
                try:
                    body = await loop.run_in_executor(
                        self._pool, fn, request)
                    if isinstance(body, str):
                        return web.Response(text=body)
                    # default=str: state payloads carry bytes ids and
                    # other non-JSON scalars; a serialization failure
                    # here must surface as a JSON error, not aiohttp's
                    # bare 500 page (it used to escape this handler)
                    return web.Response(
                        text=json.dumps(body, default=str),
                        content_type="application/json")
                except Exception as e:
                    return web.json_response({"error": str(e)},
                                             status=500)
            return handler

        def nodes(_):
            from .. import state
            return state.list_nodes()

        def actors(_):
            from .. import state
            return state.list_actors()

        def pgs(_):
            from .. import state
            return state.list_placement_groups()

        def summary(_):
            from .. import state
            return state.cluster_summary()

        def jobs_list(_):
            from .. import jobs
            return jobs.list_jobs()

        def job_submit(request):
            from .. import jobs
            # aiohttp request.read() is async; run here via the loop
            raw = asyncio.run_coroutine_threadsafe(
                request.read(), loop).result(timeout=10)
            payload = json.loads(raw or b"{}")
            job_id = jobs.submit_job(
                payload["entrypoint"],
                runtime_env=payload.get("runtime_env"))
            return {"job_id": job_id}

        def job_status(request):
            from .. import jobs
            jid = request.match_info["job_id"]
            info = jobs.get_job_info(jid)
            if info is None:
                raise ValueError(f"unknown job {jid}")
            return info

        def job_logs(request):
            from .. import jobs
            return jobs.get_job_logs(request.match_info["job_id"])

        def metrics_text(_):
            from .. import metrics
            return metrics.prometheus_text()

        def metrics_cluster(_):
            from .. import state
            return state.cluster_metrics_text()

        def logs_list(request):
            from .. import state
            return state.list_logs(request.query.get("node") or None)

        def logs_tail(request):
            from .. import state
            name = request.query.get("name", "")
            nbytes = int(request.query.get("bytes", "65536"))
            data = state.tail_log(name,
                                  request.query.get("node") or None,
                                  nbytes=nbytes)
            return data.decode("utf-8", "replace") \
                if isinstance(data, (bytes, bytearray)) else str(data)

        def timeline(_):
            # cluster-wide chrome-trace events (driver spans + every
            # node's finished-task spans)
            from ..util import tracing
            return tracing.cluster_trace_events()

        def metrics_history(request):
            # per-process metrics-history rings (counter deltas +
            # gauges), optionally reduced to one metric family's series
            from .. import state
            last = request.query.get("last")
            return state.metrics_history(
                name=request.query.get("name") or None,
                last=int(last) if last else None)

        def rpc_attribution(_):
            from .. import state
            return state.rpc_attribution()

        def serve_breakdown(_):
            from .. import state
            return state.serve_breakdown()

        def node_stats(request):
            from .. import state
            return state.node_stats(request.match_info.get("node_id"))

        def agents(_):
            from .. import state
            return state.list_agents()

        def agent_stats(request):
            from .. import state
            return state.agent_stats(request.query.get("node") or None)

        def objects(_):
            from .. import state
            return state.list_objects()

        def tasks(_):
            from .. import state
            return state.list_tasks()

        def memory(_):
            from .. import state
            # blocking() serializes with default=str; no pre-sanitizing
            # round-trip needed
            return state.memory_summary()

        import os

        client_dir = os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "client")

        async def index(_):
            # the modular client (client/ static app, the reference's
            # dashboard/client analogue) when present; the single-file
            # fallback keeps the dashboard alive in stripped installs
            page = os.path.join(client_dir, "index.html")
            if os.path.isfile(page):
                return web.FileResponse(page)
            from .index_html import INDEX_HTML
            return web.Response(text=INDEX_HTML, content_type="text/html")

        app = web.Application()
        app.router.add_get("/", index)
        if os.path.isdir(client_dir):
            app.router.add_static("/static", client_dir)
        app.router.add_get("/api/nodes/{node_id}/stats",
                           blocking(node_stats))
        def events(_):
            from .. import state
            return state.list_events()

        def fire_workflow_event(request):
            # the HTTP event provider role (reference: workflow's HTTP
            # event listener): POST /api/workflow_events/<name> with an
            # optional JSON payload unblocks waiting workflow steps
            from ..workflow import events as wf_events
            raw = asyncio.run_coroutine_threadsafe(
                request.read(), loop).result(timeout=10)
            payload = json.loads(raw) if raw else None
            name = request.match_info["name"]
            wf_events.trigger_event(name, payload)
            return {"fired": name}

        def serve_deployments(_):
            # serve REST role (reference: serve REST schema + CLI status)
            from ..serve.api import status_table
            return status_table()

        def serve_applications_get(_):
            from ..serve import schema
            return schema.status()

        def serve_applications_put(request):
            # declarative REST deploy (reference: PUT
            # /api/serve/applications/ with a ServeDeploySchema body)
            from ..serve import schema
            raw = asyncio.run_coroutine_threadsafe(
                request.read(), loop).result(timeout=10)
            handles = schema.apply_config(json.loads(raw or b"{}"))
            return {"deployed": sorted(handles)}

        app.router.add_get("/api/serve/applications",
                           blocking(serve_applications_get))
        app.router.add_put("/api/serve/applications",
                           blocking(serve_applications_put))
        app.router.add_get("/api/events", blocking(events))
        app.router.add_post("/api/workflow_events/{name}",
                            blocking(fire_workflow_event))
        app.router.add_get("/api/serve/deployments",
                           blocking(serve_deployments))
        app.router.add_get("/api/objects", blocking(objects))
        app.router.add_get("/api/tasks", blocking(tasks))
        app.router.add_get("/api/memory", blocking(memory))
        app.router.add_get("/api/nodes", blocking(nodes))
        app.router.add_get("/api/actors", blocking(actors))
        app.router.add_get("/api/placement_groups", blocking(pgs))
        app.router.add_get("/api/cluster_summary", blocking(summary))
        app.router.add_get("/api/jobs", blocking(jobs_list))
        app.router.add_post("/api/jobs", blocking(job_submit))
        app.router.add_get("/api/jobs/{job_id}", blocking(job_status))
        app.router.add_get("/api/jobs/{job_id}/logs", blocking(job_logs))
        app.router.add_get("/metrics", blocking(metrics_text))
        app.router.add_get("/metrics/cluster", blocking(metrics_cluster))
        app.router.add_get("/api/metrics/history",
                           blocking(metrics_history))
        app.router.add_get("/api/rpc_attribution",
                           blocking(rpc_attribution))
        app.router.add_get("/api/serve/breakdown",
                           blocking(serve_breakdown))
        app.router.add_get("/api/agents", blocking(agents))
        app.router.add_get("/api/agent_stats", blocking(agent_stats))
        app.router.add_get("/api/logs", blocking(logs_list))
        app.router.add_get("/api/logs/tail", blocking(logs_tail))
        app.router.add_get("/api/timeline", blocking(timeline))
        app.router.add_get(
            "/api/version",
            blocking(lambda _: {"ray_tpu": __import__(
                "ray_tpu").__version__}))

        runner = web.AppRunner(app)

        async def start():
            await runner.setup()
            site = web.TCPSite(runner, self._host, self._port)
            try:
                await site.start()
            except OSError as e:
                self._error = str(e)
            self._ready.set()

        loop.run_until_complete(start())
        if not self._error:
            loop.run_forever()


def start_dashboard(host: str = "127.0.0.1",
                    port: int = 8265) -> DashboardHead:
    return DashboardHead(host, port)
