import { api, table } from "/static/api.js";
export const title = "overview";
export function render(root) {
  root.innerHTML = `
    <div class="cards" id="cards"></div>
    <h2>nodes</h2><table id="nodes"></table>
    <h2>running tasks</h2><table id="tasks"></table>
    <h2>actors</h2><table id="actors"></table>
    <h2>placement groups</h2><table id="pgs"></table>
    <h2>object store</h2><table id="stores"></table>`;
}
export async function refresh(root) {
  const [s, nodes, tasks, actors, pgs, mem] = await Promise.all([
    api.summary(), api.nodes(), api.tasks(), api.actors(), api.pgs(),
    api.memory()]);
  const cards = Object.entries(s).filter(([, v]) => typeof v !== "object");
  root.querySelector("#cards").innerHTML = cards.map(([k, v]) =>
    `<div class="card"><div class="v">${v}</div>
     <div class="k">${k}</div></div>`).join("");
  table(root.querySelector("#nodes"), nodes);
  table(root.querySelector("#tasks"), tasks);
  table(root.querySelector("#actors"), actors);
  table(root.querySelector("#pgs"), pgs);
  const stores = (mem && mem.stores) || (mem && mem.nodes) || [];
  table(root.querySelector("#stores"),
        Array.isArray(stores) ? stores : [mem]);
}
