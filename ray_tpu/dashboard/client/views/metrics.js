import { api } from "/static/api.js";
export const title = "metrics";
export function render(root) {
  root.innerHTML = `<h2>cluster metrics (Prometheus exposition)</h2>
    <input type="text" id="filter" placeholder="filter...">
    <pre id="body"></pre>`;
  root.querySelector("#filter").oninput = () => show(root);
}
let raw = "";
function show(root) {
  const f = root.querySelector("#filter").value;
  root.querySelector("#body").textContent = f
    ? raw.split("\n").filter(l => l.includes(f)).join("\n") : raw;
}
export async function refresh(root) {
  raw = await api.metricsCluster();
  if (typeof raw !== "string") raw = JSON.stringify(raw, null, 2);
  show(root);
}
