import { api, esc } from "/static/api.js";
export const title = "timeline";
export function render(root) {
  root.innerHTML = `<h2>cluster task timeline (lifecycle spans:
    submit &rarr; schedule &rarr; dequeue &rarr; fetch &rarr; exec
    &rarr; put; newest window)</h2>
    <svg id="tl" height="10"></svg><div id="info"></div>`;
}
export async function refresh(root) {
  // Chrome-trace "X" events: ts/dur in microseconds, pid/tid = process lane
  const all = (await api.timeline()).filter(s => s.ph === "X");
  const svg = root.querySelector("#tl");
  if (!all.length) {
    root.querySelector("#info").textContent = "(no spans)";
    return;
  }
  // window-filter FIRST (newest 60s): driver-local profile spans ride a
  // different clock and would otherwise stretch the window to nonsense
  const t1 = Math.max(...all.map(s => s.ts + s.dur));
  const t0 = Math.max(Math.min(...all.map(s => s.ts)), t1 - 60e6);
  const spans = all.filter(s => s.ts + s.dur >= t0);
  const lanes = [...new Set(spans.map(s => `${s.pid}/${s.tid}`))].sort();
  const W = svg.clientWidth || 900, H = lanes.length * 18 + 6;
  svg.setAttribute("height", H);
  const x = t =>
    130 + (W - 140) * (Math.max(t, t0) - t0) / Math.max(t1 - t0, 1e-9);
  svg.innerHTML =
    lanes.map((l, i) =>
      `<text class="lane-label" x="2" y="${i * 18 + 14}">` +
      `${String(l).slice(0, 18)}</text>`).join("") +
    spans.map(s => {
      const i = lanes.indexOf(`${s.pid}/${s.tid}`);
      const cls = (s.args && s.args.interrupted)
        ? "span-rect interrupted" : "span-rect";
      return `<rect class="${cls}" x="${x(s.ts)}" y="${i * 18 + 4}"
        width="${Math.max(x(s.ts + s.dur) - x(s.ts), 1)}" height="12">
        <title>[${esc(s.cat || "task")}] ${esc(s.name || "")}
        ${(s.dur / 1e3).toFixed(1)}ms</title>
        </rect>`;
    }).join("");
  root.querySelector("#info").textContent =
    `${spans.length} spans over ${((t1 - t0) / 1e6).toFixed(2)}s on ` +
    `${lanes.length} lanes`;
}
