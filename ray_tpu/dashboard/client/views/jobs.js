import { api, table } from "/static/api.js";
export const title = "jobs";
export function render(root) {
  root.innerHTML = `<h2>jobs</h2><table id="jobs"></table>
    <h2>job logs <select id="jobsel"></select></h2><pre id="jlog">(pick)</pre>`;
  root.querySelector("#jobsel").onchange = async (e) => {
    const id = e.target.value;
    if (!id) return;
    const r = await fetch(`/api/jobs/${id}/logs`);
    root.querySelector("#jlog").textContent = await r.text();
  };
}
export async function refresh(root) {
  const jobs = await api.jobs();
  table(root.querySelector("#jobs"), jobs);
  const sel = root.querySelector("#jobsel");
  const have = new Set([...sel.options].map(o => o.value));
  for (const j of jobs) {
    const id = j.job_id || j.submission_id || j.id;
    if (id && !have.has(id)) {
      const o = document.createElement("option");
      o.value = o.textContent = id;
      sel.appendChild(o);
    }
  }
}
