import { get } from "/static/api.js";
export const title = "logs";
export function render(root) {
  root.innerHTML = `<h2>logs <select id="file"></select>
    bytes <input type="text" id="nbytes" value="65536" size="7"></h2>
    <pre id="body">(pick a file)</pre>`;
  root.querySelector("#file").onchange = () => tail(root);
}
async function tail(root) {
  const name = root.querySelector("#file").value;
  if (!name) return;
  const nbytes = root.querySelector("#nbytes").value || 65536;
  const out = await get(
    `/api/logs/tail?name=${encodeURIComponent(name)}&bytes=${nbytes}`);
  root.querySelector("#body").textContent =
    typeof out === "string" ? out : JSON.stringify(out);
}
export async function refresh(root) {
  const sel = root.querySelector("#file");
  if (!sel.options.length) {
    // /api/logs returns a flat filename list for the head's node
    const files = await get("/api/logs");
    for (const f of files) {
      const o = document.createElement("option");
      o.value = o.textContent = f;
      sel.appendChild(o);
    }
  } else if (sel.value) await tail(root);
}
