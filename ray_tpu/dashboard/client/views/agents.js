import { api, table } from "/static/api.js";
export const title = "agents";
export function render(root) {
  root.innerHTML = `<h2>per-node dashboard agents</h2>
    <table id="list"></table>
    <h2>node OS stats (agent-served, nodelet fallback)</h2>
    <table id="stats"></table>`;
}
export async function refresh(root) {
  const [agents, stats] = await Promise.all([
    api.agents(), api.agentStats()]);
  table(root.querySelector("#list"), agents);
  table(root.querySelector("#stats"),
        Array.isArray(stats) ? stats : Object.entries(stats).map(
          ([node, s]) => ({ node, ...s })));
}
