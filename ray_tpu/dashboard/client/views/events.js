import { api, table } from "/static/api.js";
export const title = "events";
export function render(root) {
  root.innerHTML = `<h2>cluster events</h2><table id="ev"></table>`;
}
export async function refresh(root) {
  const ev = await api.events();
  table(root.querySelector("#ev"), ev.slice(-200).reverse());
}
