import { api, table } from "/static/api.js";
export const title = "serve";
export function render(root) {
  root.innerHTML = `<h2>applications</h2><pre id="apps"></pre>
    <h2>deployments</h2><table id="deps"></table>`;
}
export async function refresh(root) {
  const [apps, deps] = await Promise.all([
    api.serveApps().catch(() => ({})),
    api.serveDeployments().catch(() => [])]);
  root.querySelector("#apps").textContent =
    JSON.stringify(apps, null, 2);
  table(root.querySelector("#deps"),
        Array.isArray(deps) ? deps : (deps.deployments || []));
}
