// REST wrapper for the head's /api surface (head.py routes).
export async function get(path) {
  const r = await fetch(path);
  if (!r.ok) throw new Error(`${path}: HTTP ${r.status}`);
  const ct = r.headers.get("content-type") || "";
  return ct.includes("json") ? r.json() : r.text();
}
export const api = {
  summary: () => get("/api/cluster_summary"),
  nodes: () => get("/api/nodes"),
  actors: () => get("/api/actors"),
  tasks: () => get("/api/tasks"),
  jobs: () => get("/api/jobs"),
  memory: () => get("/api/memory"),
  objects: () => get("/api/objects"),
  pgs: () => get("/api/placement_groups"),
  events: () => get("/api/events"),
  agents: () => get("/api/agents"),
  agentStats: () => get("/api/agent_stats"),
  logsList: () => get("/api/logs"),
  timeline: () => get("/api/timeline"),
  serveApps: () => get("/api/serve/applications"),
  serveDeployments: () => get("/api/serve/deployments"),
  metricsCluster: () => get("/metrics/cluster"),
};
export function esc(s) {
  // server payloads carry user-controlled strings (job entrypoints,
  // event messages, task names) — always escape before innerHTML
  return String(s).replace(/[&<>"']/g, c => ({
    "&": "&amp;", "<": "&lt;", ">": "&gt;",
    '"': "&quot;", "'": "&#39;" }[c]));
}
export function table(el, rows, cols) {
  if (!rows || !rows.length) { el.innerHTML = "<tr><td>(none)</td></tr>"; return; }
  cols = cols || Object.keys(rows[0]);
  el.innerHTML =
    "<tr>" + cols.map(c => `<th>${esc(c)}</th>`).join("") + "</tr>" +
    rows.map(r => "<tr>" + cols.map(c => {
      let v = r[c];
      if (typeof v === "object" && v !== null) v = JSON.stringify(v);
      if (v === undefined || v === null) v = "";
      const cls = v === "ALIVE" || v === "RUNNING" || v === "ok"
        ? "ok" : (v === "DEAD" || v === "FAILED" ? "bad" : "");
      return `<td class="${cls}">${esc(String(v).slice(0, 200))}</td>`;
    }).join("") + "</tr>").join("");
}
