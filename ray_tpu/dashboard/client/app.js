// Tab router + polling driver.  Each view module exports
// {title, render(root), refresh(root)}; refresh polls while visible.
import * as overview from "/static/views/overview.js";
import * as jobs from "/static/views/jobs.js";
import * as logs from "/static/views/logs.js";
import * as timeline from "/static/views/timeline.js";
import * as serve from "/static/views/serve.js";
import * as events from "/static/views/events.js";
import * as agents from "/static/views/agents.js";
import * as metrics from "/static/views/metrics.js";

const VIEWS = { overview, jobs, logs, timeline, serve, events, agents,
                metrics };
const nav = document.getElementById("nav");
const root = document.getElementById("root");
const err = document.getElementById("err");
let current = location.hash.slice(1) || "overview";
if (!VIEWS[current]) current = "overview";

for (const name of Object.keys(VIEWS)) {
  const b = document.createElement("button");
  b.textContent = VIEWS[name].title || name;
  b.dataset.v = name;
  b.onclick = () => show(name);
  nav.appendChild(b);
}

let gen = 0;          // invalidates in-flight refreshes on tab switch
let busy = false;     // one refresh at a time (no 2s-interval stacking)

async function show(name) {
  current = name;
  gen += 1;
  location.hash = name;
  for (const b of nav.children)
    b.classList.toggle("active", b.dataset.v === name);
  root.innerHTML = "";
  VIEWS[name].render(root);
  await tick();
}

async function tick() {
  if (busy) return;
  busy = true;
  const myGen = gen;
  try {
    await VIEWS[current].refresh(root);
    if (myGen === gen) err.textContent = "";
  } catch (e) {
    // a refresh raced a tab switch: its DOM is gone, not an error
    if (myGen === gen) err.textContent = String(e);
  } finally { busy = false; }
}

setInterval(() => {
  if (document.getElementById("auto").checked) tick();
}, 2000);
window.addEventListener("hashchange", () => {
  const name = location.hash.slice(1);
  if (VIEWS[name] && name !== current) show(name);
});
show(current);
