"""Single-file dashboard frontend served at ``/`` by the head.

The reference ships a React/TS client (dashboard/client/src/ — module
pages for overview, logs, events, serve, metrics); this is the
framework-native equivalent: one dependency-free HTML page with the
same module set as tabs, polling the REST API.  Views: overview
(cluster/nodes/tasks/actors/jobs/store), logs (per-node file list +
tail), timeline (finished-task spans drawn as per-worker lanes), serve
(applications/deployments/proxies), events, metrics (cluster-wide
Prometheus exposition).
"""

INDEX_HTML = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>ray-tpu dashboard</title>
<style>
  body { font-family: ui-monospace, Menlo, monospace; margin: 1.5rem;
         background: #101418; color: #d8dee6; }
  h1 { font-size: 1.1rem; } h2 { font-size: .95rem; margin: 1.2rem 0 .4rem; }
  table { border-collapse: collapse; width: 100%; font-size: .8rem; }
  th, td { border: 1px solid #2a3138; padding: .25rem .5rem;
           text-align: left; }
  th { background: #1a2026; }
  .ok { color: #7fd962; } .bad { color: #f07178; }
  #err { color: #f07178; min-height: 1em; }
  nav button { background: #1a2026; color: #d8dee6; border: 1px solid
               #2a3138; padding: .3rem .8rem; cursor: pointer;
               font-family: inherit; }
  nav button.active { background: #2a3f52; }
  .view { display: none; } .view.active { display: block; }
  pre { background: #0b0e11; padding: .6rem; overflow-x: auto;
        font-size: .75rem; max-height: 28rem; }
  select, input { background: #1a2026; color: #d8dee6;
                  border: 1px solid #2a3138; padding: .2rem; }
  svg { background: #0b0e11; width: 100%; }
  .lane-label { fill: #8a93a0; font-size: 10px; }
  .span-rect { fill: #3d7bb8; } .span-rect.interrupted { fill: #f07178; }
</style>
</head>
<body>
<h1>ray-tpu dashboard</h1>
<nav>
  <button data-v="overview" class="active">overview</button>
  <button data-v="logs">logs</button>
  <button data-v="timeline">timeline</button>
  <button data-v="serve">serve</button>
  <button data-v="events">events</button>
  <button data-v="agents">agents</button>
  <button data-v="metrics">metrics</button>
</nav>
<div id="err"></div>

<div id="overview" class="view active">
  <h2>cluster</h2><div id="summary"></div>
  <h2>nodes</h2><table id="nodes"></table>
  <h2>running tasks</h2><table id="tasks"></table>
  <h2>actors</h2><table id="actors"></table>
  <h2>jobs</h2><table id="jobs"></table>
  <h2>object store</h2><table id="stores"></table>
</div>

<div id="agents" class="view">
  <h2>per-node dashboard agents</h2><table id="agentlist"></table>
  <h2>node OS stats (agent-served, nodelet fallback)</h2>
  <table id="agentstats"></table>
</div>

<div id="logs" class="view">
  <h2>logs <select id="logfile"></select>
      <button onclick="tailLog()">tail</button></h2>
  <pre id="logbody">(pick a file)</pre>
</div>

<div id="timeline" class="view">
  <h2>cluster task timeline (lifecycle spans: submit &rarr; schedule
      &rarr; dequeue &rarr; fetch &rarr; exec &rarr; put; newest window
      &mdash; <code>ray-tpu timeline</code> dumps the full Perfetto
      trace)</h2>
  <svg id="tl" height="10"></svg>
  <div id="tlinfo"></div>
</div>

<div id="serve" class="view">
  <h2>applications</h2><table id="apps"></table>
  <h2>proxies</h2><table id="proxies"></table>
</div>

<div id="events" class="view">
  <h2>cluster events</h2><table id="evts"></table>
</div>

<div id="metrics" class="view">
  <h2>cluster metrics (Prometheus)</h2>
  <pre id="metricsbody"></pre>
</div>

<script>
async function j(url) { const r = await fetch(url); return r.json(); }
async function t(url) { const r = await fetch(url); return r.text(); }
function table(el, rows, cols) {
  const tb = document.getElementById(el);
  if (!rows || !rows.length) { tb.innerHTML = "<tr><td>(none)</td></tr>"; return; }
  let h = "<tr>" + cols.map(c => `<th>${c}</th>`).join("") + "</tr>";
  for (const r of rows)
    h += "<tr>" + cols.map(c => `<td>${fmt(r[c])}</td>`).join("") + "</tr>";
  tb.innerHTML = h;
}
function fmt(v) {
  if (v === null || v === undefined) return "";
  if (typeof v === "object") return esc(JSON.stringify(v));
  return esc(String(v));
}
function esc(s) {
  return String(s).replace(/&/g, "&amp;").replace(/</g, "&lt;")
                  .replace(/>/g, "&gt;").replace(/"/g, "&quot;");
}
let view = "overview";
for (const b of document.querySelectorAll("nav button"))
  b.onclick = () => {
    view = b.dataset.v;
    document.querySelectorAll("nav button").forEach(
      x => x.classList.toggle("active", x === b));
    document.querySelectorAll(".view").forEach(
      x => x.classList.toggle("active", x.id === view));
    refresh();
  };

async function refreshOverview() {
  const [sum, nodes, actors, tasks, jobs, mem] = await Promise.all([
    j("/api/cluster_summary"), j("/api/nodes"), j("/api/actors"),
    j("/api/tasks"), j("/api/jobs"), j("/api/memory")]);
  document.getElementById("summary").textContent = JSON.stringify(sum);
  table("nodes", nodes, ["id", "addr", "alive", "total", "avail",
                         "demand"]);
  table("tasks", tasks, ["name", "task_id", "node_id", "worker_id"]);
  table("actors", actors, ["actor_id", "class_name", "state", "name",
                           "address", "num_restarts"]);
  table("jobs", jobs, ["job_id", "status", "entrypoint"]);
  const stores = Object.entries(mem.stores || {}).map(
    ([k, v]) => ({node: k, ...v}));
  table("stores", stores, ["node", "used_bytes", "capacity_bytes",
                           "num_objects", "num_evictions",
                           "primary_pins"]);
}

async function refreshAgents() {
  const [agents, stats] = await Promise.all([
    j("/api/agents"), j("/api/agent_stats")]);
  table("agentlist", Object.entries(agents).map(
    ([node, a]) => ({node, ...a,
                     beat: new Date(a.ts * 1000).toISOString(),
                     age_s: (Date.now() / 1000 - a.ts).toFixed(1)})),
    ["node", "addr", "pid", "beat", "age_s"]);
  table("agentstats", stats.map(s => ({
    node: s.node_id, cpu_pct: s.cpu_percent,
    mem_avail_gb: s.mem_available
      ? (s.mem_available / 1e9).toFixed(1) : "",
    load: (s.load_avg || []).map(x => x.toFixed ? x.toFixed(2) : x)
      .join(" "),
    source: s.error ? "ERROR" : (s.agent_pid ? "agent"
                                  : (s.agent || "nodelet")),
    error: s.error || "",
    logs: (s.log_files || []).length})),
    ["node", "cpu_pct", "mem_avail_gb", "load", "source", "error",
     "logs"]);
}

async function refreshLogs() {
  const files = await j("/api/logs");
  const sel = document.getElementById("logfile");
  const cur = sel.value;
  sel.innerHTML = files.map(f => `<option>${esc(f)}</option>`).join("");
  if (files.includes(cur)) sel.value = cur;
}
async function tailLog() {
  const name = document.getElementById("logfile").value;
  if (!name) return;
  document.getElementById("logbody").textContent =
    await t(`/api/logs/tail?name=${encodeURIComponent(name)}`);
}

async function refreshTimeline() {
  const all = (await j("/api/timeline")).filter(e => e.ph === "X");
  const svg = document.getElementById("tl");
  if (!all.length) { svg.setAttribute("height", 10);
    document.getElementById("tlinfo").textContent = "(no spans yet)";
    return; }
  const t1 = Math.max(...all.map(e => e.ts + e.dur));
  const t0 = Math.max(Math.min(...all.map(e => e.ts)), t1 - 60e6);
  // window-filter FIRST: lanes and counts must describe what is drawn
  // (driver-local profile spans use a different clock and would
  // otherwise create permanently empty lanes)
  const evts = all.filter(e => e.ts + e.dur >= t0);
  const lanes = [...new Set(evts.map(e => `${e.pid}/${e.tid}`))].sort();
  const H = 16, W = svg.clientWidth || 900;
  svg.setAttribute("height", lanes.length * H + 6);
  let body = "";
  for (const e of evts) {
    const y = lanes.indexOf(`${e.pid}/${e.tid}`) * H + 3;
    const x = 140 + (Math.max(e.ts, t0) - t0) / (t1 - t0 + 1) * (W - 150);
    const w = Math.max(1, e.dur / (t1 - t0 + 1) * (W - 150));
    const cls = (e.args && e.args.interrupted) ?
      "span-rect interrupted" : "span-rect";
    body += `<rect class="${cls}" x="${x}" y="${y}" width="${w}"` +
            ` height="${H - 5}"><title>[${esc(e.cat || "task")}] ` +
            `${esc(e.name)} ${(e.dur / 1000).toFixed(1)}ms</title></rect>`;
  }
  lanes.forEach((l, i) => {
    body += `<text class="lane-label" x="2" y="${i * H + 12}">` +
            `${esc(l.slice(0, 22))}</text>`;
  });
  svg.innerHTML = body;
  document.getElementById("tlinfo").textContent =
    `${evts.length} spans, ${lanes.length} lanes, window ` +
    `${((t1 - t0) / 1e6).toFixed(1)}s`;
}

async function refreshServe() {
  const st = await j("/api/serve/applications");
  const apps = Object.entries(st.applications || {}).map(
    ([name, a]) => ({name, status: a.status, ...a.deployment}));
  table("apps", apps, ["name", "status", "num_replicas",
                       "route_prefix"]);
  const proxies = Object.entries(st.proxies || {}).map(
    ([node, addr]) => ({node, addr}));
  table("proxies", proxies, ["node", "addr"]);
}

async function refreshEvents() {
  const rows = (await j("/api/events")).map(
    e => ({...e, time: e.ts ? new Date(e.ts * 1000).toISOString() : ""}));
  table("evts", rows, ["time", "severity", "source", "message"]);
}

async function refreshMetrics() {
  document.getElementById("metricsbody").textContent =
    await t("/metrics/cluster");
}

const refreshers = {overview: refreshOverview, logs: refreshLogs,
                    timeline: refreshTimeline, serve: refreshServe,
                    events: refreshEvents, agents: refreshAgents,
                    metrics: refreshMetrics};
async function refresh() {
  try {
    await refreshers[view]();
    document.getElementById("err").textContent = "";
  } catch (e) {
    document.getElementById("err").textContent = "refresh failed: " + e;
  }
}
refresh();
setInterval(refresh, 2000);
</script>
</body>
</html>
"""
