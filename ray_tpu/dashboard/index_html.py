"""Single-file dashboard frontend served at ``/`` by the head.

The reference ships a React/TS client (dashboard/client/src/); this is the
framework-native minimal equivalent: one dependency-free HTML page that
polls the REST API (/api/cluster_summary, /api/nodes, /api/actors,
/api/tasks, /api/jobs, /api/memory) and renders live tables.
"""

INDEX_HTML = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>ray-tpu dashboard</title>
<style>
  body { font-family: ui-monospace, Menlo, monospace; margin: 1.5rem;
         background: #101418; color: #d8dee6; }
  h1 { font-size: 1.1rem; } h2 { font-size: 0.95rem; margin: 1.2rem 0 .4rem; }
  table { border-collapse: collapse; width: 100%; font-size: .8rem; }
  th, td { border: 1px solid #2a3138; padding: .25rem .5rem;
           text-align: left; }
  th { background: #1a2026; }
  .ok { color: #7fd962; } .bad { color: #f07178; }
  #err { color: #f07178; min-height: 1em; }
</style>
</head>
<body>
<h1>ray-tpu dashboard</h1>
<div id="err"></div>
<h2>cluster</h2><div id="summary"></div>
<h2>nodes</h2><table id="nodes"></table>
<h2>running tasks</h2><table id="tasks"></table>
<h2>actors</h2><table id="actors"></table>
<h2>jobs</h2><table id="jobs"></table>
<h2>object store</h2><table id="stores"></table>
<script>
async function j(url) { const r = await fetch(url); return r.json(); }
function table(el, rows, cols) {
  const t = document.getElementById(el);
  if (!rows || !rows.length) { t.innerHTML = "<tr><td>(none)</td></tr>"; return; }
  let h = "<tr>" + cols.map(c => `<th>${c}</th>`).join("") + "</tr>";
  for (const r of rows)
    h += "<tr>" + cols.map(c => `<td>${fmt(r[c])}</td>`).join("") + "</tr>";
  t.innerHTML = h;
}
function fmt(v) {
  if (v === null || v === undefined) return "";
  if (typeof v === "object") return JSON.stringify(v);
  return String(v);
}
async function refresh() {
  try {
    const [sum, nodes, actors, tasks, jobs, mem] = await Promise.all([
      j("/api/cluster_summary"), j("/api/nodes"), j("/api/actors"),
      j("/api/tasks"), j("/api/jobs"), j("/api/memory")]);
    document.getElementById("summary").textContent = JSON.stringify(sum);
    table("nodes", nodes, ["id", "addr", "alive", "total", "available"]);
    table("tasks", tasks, ["name", "task_id", "node_id", "worker_id"]);
    table("actors", actors, ["actor_id", "class_name", "state", "name",
                             "address", "num_restarts"]);
    table("jobs", jobs, ["job_id", "status", "entrypoint"]);
    const stores = Object.entries(mem.stores || {}).map(
      ([k, v]) => ({node: k, ...v}));
    table("stores", stores, ["node", "used_bytes", "capacity_bytes",
                             "num_objects", "num_evictions",
                             "primary_pins"]);
    document.getElementById("err").textContent = "";
  } catch (e) {
    document.getElementById("err").textContent = "refresh failed: " + e;
  }
}
refresh();
setInterval(refresh, 2000);
</script>
</body>
</html>
"""
