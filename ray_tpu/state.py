"""State observability API.

Capability mirror of the reference's state API (`ray list actors/tasks/...`,
`python/ray/experimental/state/api.py:112,729,1269`, aggregator
`dashboard/state_aggregator.py`) — reads cluster state from the controller.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from .api import _ensure_initialized


def list_nodes() -> List[Dict[str, Any]]:
    return _ensure_initialized().controller.call("list_nodes")


def list_actors() -> List[Dict[str, Any]]:
    return _ensure_initialized().controller.call("list_actors")


def list_placement_groups() -> List[Dict[str, Any]]:
    return _ensure_initialized().controller.call("list_placement_groups")


def list_jobs() -> List[Dict[str, Any]]:
    from . import jobs
    return jobs.list_jobs()


def summarize_actors() -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for a in list_actors():
        key = a.get("state", "UNKNOWN")
        counts[key] = counts.get(key, 0) + 1
    return counts


def summarize_nodes() -> Dict[str, Any]:
    ns = list_nodes()
    return {
        "total": len(ns),
        "alive": sum(1 for n in ns if n.get("alive")),
        "resources": {
            k: sum(n["total"].get(k, 0) for n in ns if n.get("alive"))
            for n in ns for k in n.get("total", {})
        } if ns else {},
    }


def cluster_summary() -> Dict[str, Any]:
    return {
        "nodes": summarize_nodes(),
        "actors": summarize_actors(),
        "placement_groups": len(list_placement_groups()),
    }
