"""State observability API.

Capability mirror of the reference's state API (`ray list actors/tasks/...`,
`python/ray/experimental/state/api.py:112,729,1269`, aggregator
`dashboard/state_aggregator.py`) — reads cluster state from the controller.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from .api import _ensure_initialized


def list_nodes() -> List[Dict[str, Any]]:
    """Node membership rows.  Each row carries ``state`` (ALIVE |
    SUSPECT | DRAINING | DEAD), a ``health`` dict (heartbeat age plus
    the heartbeat-timeout / suspect-grace / probe-fanout knobs in
    force), ``unreachable_peers`` when the node reported severed links,
    and, while a drain or suspect quarantine is in progress, its
    progress (``drain`` dict / ``suspect_for_s`` + ``peers_reaching``)."""
    return _ensure_initialized().controller.call("list_nodes")


def nodes() -> List[Dict[str, Any]]:
    """Alias of :func:`list_nodes` (reference naming: state.nodes)."""
    return list_nodes()


def list_actors() -> List[Dict[str, Any]]:
    return _ensure_initialized().controller.call("list_actors")


def actors() -> List[Dict[str, Any]]:
    """Alias of :func:`list_actors` (reference naming: state.actors).

    Rows carry restart/containment columns: ``num_restarts`` (lifetime
    restart count) and ``quarantined`` (True once the controller has
    crash-loop-quarantined the actor; callers get a typed
    ``ActorQuarantinedError`` instead of endless restarts).
    """
    return list_actors()


def quarantine_list() -> List[Dict[str, Any]]:
    """Poison-task / crash-loop quarantine records (evidence trails)."""
    return _ensure_initialized().controller.call("quarantine_list")


def list_placement_groups() -> List[Dict[str, Any]]:
    return _ensure_initialized().controller.call("list_placement_groups")


def list_jobs() -> List[Dict[str, Any]]:
    from . import jobs
    return jobs.list_jobs()


def summarize_actors() -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for a in list_actors():
        key = a.get("state", "UNKNOWN")
        counts[key] = counts.get(key, 0) + 1
    return counts


def summarize_nodes() -> Dict[str, Any]:
    ns = list_nodes()
    return {
        "total": len(ns),
        "alive": sum(1 for n in ns if n.get("alive")),
        "suspect": sum(1 for n in ns if n.get("state") == "SUSPECT"),
        "draining": sum(1 for n in ns if n.get("state") == "DRAINING"),
        "unreachable_pairs": sorted(
            (n["id"][:12], dst[:12]) for n in ns
            for dst in n.get("unreachable_peers", ())),
        "resources": {
            k: sum(n["total"].get(k, 0) for n in ns if n.get("alive"))
            for n in ns for k in n.get("total", {})
        } if ns else {},
    }


def cluster_summary() -> Dict[str, Any]:
    return {
        "nodes": summarize_nodes(),
        "actors": summarize_actors(),
        "placement_groups": len(list_placement_groups()),
        "tasks": summarize_tasks(),
    }


def list_controllers() -> List[Dict[str, Any]]:
    """One row per controller process this driver knows about (the
    leader plus its hot standbys — core/ha.py): role, epoch, and — for
    the leader — WAL replication mode/lag.  Dead or unreachable
    controllers are reported as such rather than omitted."""
    from .core import rpc as rpc_mod
    core = _ensure_initialized()
    eps = []
    try:
        eps = core.controller.endpoints()
    except Exception:
        pass
    if not eps:
        eps = rpc_mod.parse_endpoints(core.controller_addr)
    rows = []
    for host, port in eps:
        addr = f"{host}:{port}"
        try:
            conn = core.lt.run(rpc_mod.connect(host, port, retries=1))
            try:
                st = core.lt.run(conn.call("ha_status", {}, timeout=5))
            finally:
                core.lt.run(conn.close())
            rows.append({"addr": addr, **(st or {})})
        except Exception as e:
            rows.append({"addr": addr, "role": "unreachable",
                         "error": str(e)})
    return rows


def cluster_info() -> Dict[str, Any]:
    """Control-plane + membership overview: a row for EVERY controller
    (leader and standby, with epoch and replication lag) plus the node
    table — the `ray-tpu controller status` data source."""
    return {"controllers": list_controllers(), "nodes": list_nodes()}


# -------------------------------------------------- per-node deep state
def _node_call(addr: str, method: str, data: Optional[dict] = None,
               timeout: float = 10.0):
    """One RPC to a nodelet (the aggregator role of the reference's
    dashboard/state_aggregator.py querying per-node agents).  Connections
    are pooled on the core (dashboards poll every couple of seconds — no
    per-poll connect/teardown churn); a dead conn is dropped and redialed
    once."""
    from .core import rpc as rpc_mod
    core = _ensure_initialized()
    lock = core._state_conns_lock
    pool = core._state_conns
    host, port = addr.rsplit(":", 1)
    for attempt in (0, 1):
        with lock:
            conn = pool.get(addr)
        if conn is None or conn.closed:
            conn = core.lt.run(rpc_mod.connect(host, int(port), retries=3))
            with lock:
                stale = pool.get(addr)
                if stale is not None and stale is not conn \
                        and not stale.closed:
                    # lost a dial race: keep the winner, close ours
                    core.lt.run(conn.close())
                    conn = stale
                else:
                    pool[addr] = conn
        try:
            return core.lt.run(conn.call(method, data or {},
                                         timeout=timeout))
        except TimeoutError:
            # A slow reply proves nothing about the transport — the conn
            # is shared; closing it would kill other threads' in-flight
            # calls (and TimeoutError IS an OSError on py3.11+, so it
            # must be excluded from the broken-transport handling below).
            raise
        except (rpc_mod.RpcError, OSError):
            with lock:
                if pool.get(addr) is conn:
                    pool.pop(addr, None)
            try:
                core.lt.run(conn.close())  # drop the fd, not just the ref
            except Exception:
                pass
            if attempt:
                raise


def cluster_metrics_text() -> str:
    """Prometheus exposition aggregated cluster-wide: this process's
    registry + the controller's + every alive nodelet's (reference: the
    ~90-metric runtime battery of metric_defs.cc, exported per
    component; here one scrape endpoint serves the union)."""
    from . import metrics
    parts = [metrics.prometheus_text()]
    core = _ensure_initialized()
    try:
        parts.append(core.controller.call("metrics_text", timeout=10.0))
    except Exception:
        pass
    for n in list_nodes():
        if not n.get("alive"):
            continue
        try:
            parts.append(_node_call(n["addr"], "metrics_text"))
        except Exception:
            continue
    # de-duplicate HELP/TYPE headers repeated across process registries
    seen: set = set()
    out: List[str] = []
    for part in parts:
        for line in (part or "").splitlines():
            if line.startswith("#"):
                if line in seen:
                    continue
                seen.add(line)
            elif line in seen:
                continue   # identical sample from an earlier registry
            else:
                seen.add(line)
            out.append(line)
    return "\n".join(out) + "\n"


def metrics_history(name: Optional[str] = None,
                    last: Optional[int] = None,
                    deployment: Optional[str] = None,
                    kind: str = "counters") -> Dict[str, Any]:
    """Cluster-wide metrics history: each server process's bounded ring
    of fixed-interval samples (counter deltas + gauges;
    core/metrics_history.py), keyed by process label.  With ``name``,
    a ``series`` view extracts that one metric family per process —
    the signal source the serve autoscale loop and ``ray-tpu top``
    read.  ``deployment`` filters the series to samples carrying that
    ``deployment=`` label (serve engine occupancy/waiting pushes are
    labeled per deployment and replica), so per-deployment series come
    back without client-side regex over the merged rings; ``kind``
    picks "counters" or "gauges" (serve engine samples are gauges)."""
    from .core import metrics_history as mh
    core = _ensure_initialized()
    procs: Dict[str, Any] = {}
    try:
        procs["controller"] = core.controller.call(
            "metrics_history", {"last": last}, timeout=10.0)
    except Exception:
        pass
    for n in list_nodes():
        if not n.get("alive"):
            continue
        try:
            r = _node_call(n["addr"], "metrics_history", {"last": last})
            procs[r.get("label") or f"nodelet@{n['id'][:8]}"] = r
        except Exception:
            continue
    out: Dict[str, Any] = {
        "interval_s": next((p.get("interval_s") for p in procs.values()),
                           None),
        "processes": procs,
    }
    if name:
        labels = {"deployment": deployment} if deployment else None
        out["series"] = {
            label: mh.series(p.get("samples", []), name, kind=kind,
                             labels=labels)
            for label, p in procs.items()}
    return out


def _prom_samples(text: str) -> Dict[str, list]:
    """Parse Prometheus exposition text into name -> [(tags, value)].
    Minimal by design: our own exposition format (metrics.py) — one
    sample per line, ``label="value"`` pairs, no escapes."""
    import re
    line_re = re.compile(
        r"^([A-Za-z_:][A-Za-z0-9_:]*)(?:\{([^}]*)\})?\s+(\S+)$")
    tag_re = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)="([^"]*)"')
    out: Dict[str, list] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = line_re.match(line)
        if m is None:
            continue
        try:
            val = float(m.group(3))
        except ValueError:
            continue
        tags = dict(tag_re.findall(m.group(2) or ""))
        out.setdefault(m.group(1), []).append((tags, val))
    return out


#: serve_breakdown's named phases, in pipeline order
SERVE_PHASES = ("cold_start", "queue", "admission", "prefill",
                "decode_dispatch", "stream_drain")


def serve_breakdown() -> Dict[str, Any]:
    """Per-deployment serve time attribution: where does a served
    millisecond-per-token actually go?  Reads the cluster scrape
    (`cluster_metrics_text`) and reduces the data-plane flight
    instruments — engine phase counters, proxy TTFT/ITL histograms,
    token counters, per-program MFU — to one table per deployment:

    * ``phases_s`` / ``ms_per_token``: cold_start (lazy replica
      construction — model init and first compiles land inside the
      first request's TTFT), queue (enqueue -> first prefill chunk),
      admission (first token -> decode slot), prefill (chunk program
      wall), decode_dispatch (decode/draft/verify/insert program
      wall), stream_drain (client-observed inter-token time not
      explained by decode dispatch: queue depth + RPC + SSE);
    * ``coverage``: attributed seconds over client-measured seconds
      (TTFT sum + ITL sum) — the honesty metric.  Healthy is >= 0.9:
      the engine-side marks explain at least 90% of what clients
      actually waited; a gap means an uninstrumented phase;
    * ``mfu``: per-program model-FLOPs-utilization gauges.

    Surfaces: `ray-tpu top` breakdown panel, ``/api/serve/breakdown``,
    ``bench.py --serve-breakdown`` (SERVE_BENCH.json)."""
    samples = _prom_samples(cluster_metrics_text())
    per: Dict[str, Dict[str, Any]] = {}

    def acc(dep: str) -> Dict[str, Any]:
        return per.setdefault(dep, {
            "phases_s": dict.fromkeys(SERVE_PHASES, 0.0),
            "tokens": 0.0, "requests": 0.0,
            "ttft_s": 0.0, "itl_s": 0.0, "mfu": {}})

    for tags, v in samples.get("ray_tpu_serve_phase_seconds_total", ()):
        a = acc(tags.get("deployment", "?"))
        ph = tags.get("phase", "")
        if ph in a["phases_s"]:
            a["phases_s"][ph] += v
    for tags, v in samples.get("ray_tpu_serve_tokens_total", ()):
        acc(tags.get("deployment", "?"))["tokens"] += v
    for name, key in (("ray_tpu_serve_ttft_seconds_sum", "ttft_s"),
                      ("ray_tpu_serve_itl_seconds_sum", "itl_s")):
        for tags, v in samples.get(name, ()):
            acc(tags.get("deployment", "?"))[key] += v
    for tags, v in samples.get("ray_tpu_serve_ttft_seconds_count", ()):
        acc(tags.get("deployment", "?"))["requests"] += v
    for tags, v in samples.get("ray_tpu_mfu_ratio", ()):
        acc(tags.get("deployment", "?"))["mfu"][
            tags.get("program", "?")] = v

    deployments: Dict[str, Any] = {}
    for dep, a in sorted(per.items()):
        ph = a["phases_s"]
        # inter-token time clients saw but decode dispatch does not
        # explain: slot queueing, chunk RPC, SSE write — the drain tail
        ph["stream_drain"] = max(0.0, a["itl_s"]
                                 - ph["decode_dispatch"])
        measured = a["ttft_s"] + a["itl_s"]
        attributed = sum(ph.values())
        tokens = a["tokens"]
        deployments[dep] = {
            "tokens": int(tokens),
            "requests": int(a["requests"]),
            "measured_s": round(measured, 6),
            "attributed_s": round(attributed, 6),
            "coverage": (round(attributed / measured, 4)
                         if measured > 0 else None),
            "phases_s": {k: round(v, 6) for k, v in ph.items()},
            "ms_per_token": {
                k: (round(v / tokens * 1e3, 4) if tokens else None)
                for k, v in ph.items()},
            "mfu": {k: round(v, 4) for k, v in sorted(a["mfu"].items())},
        }
    return {"phases": list(SERVE_PHASES), "deployments": deployments}


def rpc_attribution() -> Dict[str, Any]:
    """Per-RPC control-plane attribution: for the controller and every
    alive nodelet, the per-op dispatch table (count, errors, total
    handler seconds, avg/p50/p99/max latency, payload bytes — sorted by
    total time), plus WAL append/fsync timing and asyncio loop lag.
    The 'where does control-plane time go' view SCALE_r06 reads before
    and after (ROADMAP item 4)."""
    core = _ensure_initialized()
    out: Dict[str, Any] = {"nodes": {}}
    try:
        out["controller"] = core.controller.call("rpc_attribution", {},
                                                 timeout=10.0)
    except Exception as e:
        out["controller"] = {"error": str(e)}
    for n in list_nodes():
        if not n.get("alive"):
            continue
        try:
            out["nodes"][n["id"][:12]] = _node_call(n["addr"],
                                                    "rpc_attribution")
        except Exception:
            continue
    return out


def top_rpc_ops(k: int = 3) -> List[Dict[str, Any]]:
    """The controller's top-``k`` RPC handlers by total handler time."""
    attr = rpc_attribution().get("controller") or {}
    return list(attr.get("ops") or [])[:k]


def debug_capture(reason: str = "") -> Dict[str, Any]:
    """Capture a flight-recorder bundle NOW (manual grab; bypasses the
    per-trigger rate limit).  Returns {"ok", "path"}."""
    return _ensure_initialized().controller.call(
        "debug_capture", {"trigger": "manual", "reason": reason},
        timeout=30.0)


def node_stats(node_id: Optional[str] = None) -> List[Dict[str, Any]]:
    """Deep per-node stats: worker tables, running tasks, store usage
    (reference: dashboard reporter/agent per-node stats)."""
    out = []
    for n in list_nodes():
        if not n.get("alive"):
            continue
        if node_id is not None and n["id"] != node_id:
            continue
        try:
            out.append(_node_call(n["addr"], "node_stats"))
        except Exception as e:
            out.append({"node_id": n["id"], "error": str(e)})
    return out


# ------------------------------------------------ dashboard agents
def _agent_fresh(info: Dict[str, Any]) -> bool:
    """A registration is live if its heartbeat is recent — a SIGKILLed
    agent never deregisters, so the 'ts' it refreshes every beat is the
    liveness signal (3 missed beats + slack = dead)."""
    import time as _time
    hb = float(info.get("heartbeat_s", 2.0))
    return _time.time() - float(info.get("ts", 0)) < 3.0 * hb + 2.0


def list_agents(include_stale: bool = False) -> Dict[str, Dict[str, Any]]:
    """Per-node dashboard agents registered in controller KV
    (reference: the head's per-node agent table, dashboard/head.py
    node-agent discovery through the GCS)."""
    import json as _json

    from .dashboard.agent import AGENT_KV_NS, AGENT_KV_PREFIX
    core = _ensure_initialized()
    keys = core.controller.call("kv_keys", {"ns": AGENT_KV_NS,
                                            "prefix": AGENT_KV_PREFIX})
    out = {}
    for key in keys:
        raw = core.controller.call("kv_get", {"ns": AGENT_KV_NS,
                                              "key": key})
        if raw is None:
            continue
        info = _json.loads(raw)
        if include_stale or _agent_fresh(info):
            out[key[len(AGENT_KV_PREFIX):]] = info
    return out


def agent_stats(node_id: Optional[str] = None) -> List[Dict[str, Any]]:
    """OS-level node stats served by the per-node agents, falling back
    to the nodelet scrape path for nodes whose agent is dead or absent
    — logs/stats stay served either way (the reference's head degrades
    the same direction when an agent is unreachable)."""
    agents = list_agents()
    out = []
    for n in list_nodes():
        if not n.get("alive"):
            continue
        if node_id is not None and n["id"] != node_id:
            continue
        agent = agents.get(n["id"])
        if agent is not None:
            try:
                out.append(_node_call(agent["addr"], "agent_stats",
                                      timeout=5.0))
                continue
            except Exception:
                pass    # dead agent: fall through to the nodelet
        try:
            stats = _node_call(n["addr"], "node_stats")
            stats["agent"] = "fallback:nodelet"
            out.append(stats)
        except Exception as e:
            out.append({"node_id": n["id"], "error": str(e)})
    return out


# ---------------------------------------------------- cluster timeline
def _trace_span_events() -> List[Dict[str, Any]]:
    """Every process's flushed lifecycle spans, merged from the
    controller KV (namespace ``trace``, one key per process).  The
    driver's own buffer is flushed synchronously first so a dump taken
    right after a burst is complete."""
    import json as _json

    from .util import tracing
    core = _ensure_initialized()
    payload = tracing.kv_payload()
    if payload is not None:
        try:
            core.controller.call("kv_put", {
                "ns": tracing.TRACE_KV_NS, "key": tracing.kv_key(),
                "value": payload, "persist": False})
        except Exception:
            tracing.mark_dirty()
    events: List[Dict[str, Any]] = []
    for key in core.controller.call("kv_keys",
                                    {"ns": tracing.TRACE_KV_NS,
                                     "prefix": ""}):
        raw = core.controller.call("kv_get", {"ns": tracing.TRACE_KV_NS,
                                              "key": key})
        if raw:
            try:
                events.extend(_json.loads(raw))
            except ValueError:
                continue
    return events


def _node_task_span_events() -> List[Dict[str, Any]]:
    """Legacy per-node finished-task spans (nodelet ``task_spans``
    buffers) as Chrome events — still the only source for tasks whose
    worker died mid-flight (``interrupted`` spans)."""
    events: List[Dict[str, Any]] = []
    try:
        for n in list_nodes():
            if not n.get("alive"):
                continue
            for sp in _node_call(n["addr"], "task_spans"):
                events.append({
                    "name": sp["name"], "cat": "task", "ph": "X",
                    "ts": sp["start"] * 1e6,
                    "dur": max(0.0, (sp["end"] - sp["start"])) * 1e6,
                    "pid": "node:" + n["id"][:8],
                    "tid": "worker:" + sp["worker_id"][:8],
                    "args": {"task_id": sp.get("task_id", ""),
                             "interrupted": sp.get("interrupted", False)},
                })
    except Exception:
        pass
    return events


def _clock_offsets() -> Dict[str, float]:
    """node-id-prefix (8 hex) → estimated wall-clock offset in seconds
    (node − controller), from the heartbeat RTT-midpoint estimates the
    controller folds into its node rows."""
    try:
        return {n["id"][:8]: float(n.get("clock_offset_s") or 0.0)
                for n in list_nodes()}
    except Exception:
        return {}


def apply_clock_offsets(events: List[Dict[str, Any]],
                        offsets: Dict[str, float]) -> None:
    """Shift each span onto the CONTROLLER clock in place: a span's pid
    names its process ("kind@<node8>" lifecycle spans, "node:<node8>"
    legacy task spans); subtracting that node's offset re-aligns
    cross-host spans into causal order (a follower whose clock runs
    100ms ahead otherwise renders its exec span before the submit that
    caused it)."""
    if not offsets:
        return
    for e in events:
        pid = str(e.get("pid") or "")
        node8 = ""
        if "@" in pid:
            node8 = pid.rsplit("@", 1)[1][:8]
        elif pid.startswith("node:"):
            node8 = pid[5:][:8]
        off = offsets.get(node8)
        if off:
            e["ts"] = e.get("ts", 0) - off * 1e6


def timeline() -> Dict[str, Any]:
    """Cluster-wide task timeline as a Chrome-trace dict (reference:
    `ray timeline` / chrome_tracing_dump, _private/state.py:414).

    Merges every process's lifecycle spans (submit → schedule → dequeue
    → fetch → exec → put, plus serve/train workload spans) with the
    legacy per-node finished-task spans, ordered by timestamp with
    per-process pid/tid attribution, re-aligned onto the controller
    clock via the heartbeat-estimated per-host offsets.  The returned
    dict serializes directly to a file loadable in
    https://ui.perfetto.dev or chrome://tracing."""
    events = _trace_span_events() + _node_task_span_events()
    apply_clock_offsets(events, _clock_offsets())
    events.sort(key=lambda e: e.get("ts", 0))
    pids: List[Any] = []
    for e in events:
        p = e.get("pid")
        if p not in pids:
            pids.append(p)
    meta = [{"ph": "M", "name": "process_name", "pid": p, "tid": 0,
             "args": {"name": str(p)}} for p in pids]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def list_tasks() -> List[Dict[str, Any]]:
    """RUNNING tasks cluster-wide with node attribution (reference:
    `ray list tasks`, experimental/state/api.py)."""
    tasks = []
    for ns in node_stats():
        for t in ns.get("running_tasks", []):
            tasks.append({**t, "node_id": ns.get("node_id")})
    return tasks


def summarize_tasks() -> Dict[str, Any]:
    """Finished-task counts by function + currently running count
    (reference: `ray summary tasks`, state/api.py:1269)."""
    counts: Dict[str, int] = {}
    running = 0
    for ns in node_stats():
        running += len(ns.get("running_tasks", []))
        for name, n in ns.get("task_counts", {}).items():
            counts[name] = counts.get(name, 0) + n
    return {"finished_by_func": counts, "running": running}


def list_events(severity: Optional[str] = None,
                limit: int = 200) -> List[Dict[str, Any]]:
    """Structured cluster events, newest last (reference: the event
    framework, src/ray/util/event.h + dashboard/modules/event)."""
    return _ensure_initialized().controller.call(
        "list_events", {"severity": severity, "limit": limit})


def report_event(message: str, *, severity: str = "INFO",
                 source: str = "user", **meta) -> None:
    """Emit a user event into the cluster event log."""
    _ensure_initialized().controller.call(
        "report_event", {"severity": severity, "source": source,
                         "message": message, "meta": meta})


def list_objects() -> List[Dict[str, Any]]:
    """Cluster object table: size, locations, borrow holders, deferred
    frees (reference: `ray list objects`)."""
    return _ensure_initialized().controller.call("list_objects", {})


def memory_summary() -> Dict[str, Any]:
    """`ray memory`-style dump: object table + outstanding borrows +
    per-node store usage (reference: python/ray/_private/internal_api.py
    memory_summary)."""
    core = _ensure_initialized()
    stores = {}
    for ns in node_stats():
        if "store" in ns:
            stores[ns["node_id"]] = {**ns["store"],
                                     "primary_pins": ns.get("primary_pins")}
    return {
        "objects": list_objects(),
        "refs": core.controller.call("ref_counts", {}),
        "stores": stores,
    }


def _agent_for_addr(addr: str) -> Optional[str]:
    """Agent address for a nodelet address, if a live agent registered.
    ONE kv_get for the addressed node — not a full agent-table scan per
    log poll."""
    import json as _json

    from .dashboard.agent import AGENT_KV_NS, AGENT_KV_PREFIX
    try:
        node_id = next((n["id"] for n in list_nodes()
                        if n["addr"] == addr), None)
        if node_id is None:
            return None
        raw = _ensure_initialized().controller.call(
            "kv_get", {"ns": AGENT_KV_NS,
                       "key": AGENT_KV_PREFIX + node_id})
        if raw is None:
            return None
        info = _json.loads(raw)
        return info["addr"] if _agent_fresh(info) else None
    except Exception:
        return None


def list_logs(node_addr: Optional[str] = None) -> List[str]:
    """Per-process log files on a node's session dir (reference:
    LogMonitor's file set, `ray logs`) — served by the node's dashboard
    agent when one is alive, by the nodelet otherwise."""
    nodes = list_nodes()
    addr = node_addr or next(
        (n["addr"] for n in nodes if n.get("alive")), None)
    if addr is None:
        return []
    agent_addr = _agent_for_addr(addr)
    if agent_addr is not None:
        try:
            return _node_call(agent_addr, "list_logs",
                              timeout=5.0).get("files", [])
        except Exception:
            pass
    return _node_call(addr, "tail_log", {}).get("files", [])


def tail_log(name: str, node_addr: Optional[str] = None,
             nbytes: int = 65536) -> bytes:
    """Tail one per-process log file (reference: `ray logs <file>`) —
    agent-served with nodelet fallback, like :func:`list_logs`."""
    nodes = list_nodes()
    addr = node_addr or next(
        (n["addr"] for n in nodes if n.get("alive")), None)
    if addr is None:
        raise RuntimeError("no alive node")
    agent_addr = _agent_for_addr(addr)
    if agent_addr is not None:
        try:
            r = _node_call(agent_addr, "tail_log",
                           {"name": name, "bytes": nbytes}, timeout=5.0)
            if "error" not in r:
                return r["data"]
        except Exception:
            pass
    r = _node_call(addr, "tail_log", {"name": name, "bytes": nbytes})
    if "error" in r:
        raise RuntimeError(r["error"])
    return r["data"]
