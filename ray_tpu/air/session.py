"""Training session: the in-train-loop API.

Capability mirror of the reference's `air/session.py:41,94`
(`session.report(metrics, checkpoint=...)`, rank getters) — the user's
train function calls these; the backing `_Session` is installed per worker
by the Train backend executor and streams results back to the driver.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, Optional

from .checkpoint import Checkpoint


class _Session:
    def __init__(self, *, world_rank: int = 0, local_rank: int = 0,
                 world_size: int = 1, node_rank: int = 0,
                 trial_name: str = "default", dataset_shard=None):
        self.world_rank = world_rank
        self.local_rank = local_rank
        self.world_size = world_size
        self.node_rank = node_rank
        self.trial_name = trial_name
        self.dataset_shard = dataset_shard
        self.queue: "queue.Queue" = queue.Queue()
        self.stop_event = threading.Event()
        self.last_checkpoint: Optional[Checkpoint] = None
        self.iteration = 0
        self._last_report_t: Optional[float] = None
        # elastic recovery (train/elastic.py): a per-rank background
        # snapshotter installed by TrainWorker.init_session; report()
        # only ENQUEUES — serialization/replication stay off-step-path
        self.elastic = None

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None):
        import time as _time

        from ..util import tracing
        self.iteration += 1
        # per-step span: report() marks step boundaries, so each span
        # covers one train step on this worker's timeline lane
        now = _time.time()
        if self._last_report_t is not None:
            tracing.record_span(f"train_step::{self.trial_name}", "train",
                                self._last_report_t, now,
                                iteration=self.iteration,
                                rank=self.world_rank)
        self._last_report_t = now
        if checkpoint is not None:
            self.last_checkpoint = checkpoint
        self.queue.put({"metrics": dict(metrics), "checkpoint": checkpoint,
                        "iteration": self.iteration})
        if self.elastic is not None and checkpoint is not None:
            self.elastic.maybe_snapshot(self.iteration, checkpoint)
        if self.stop_event.is_set():
            raise SystemExit("session stopped by driver")


_session: threading.local = threading.local()


def _set_session(s: Optional[_Session]):
    _session.value = s


def _get_session() -> Optional[_Session]:
    return getattr(_session, "value", None)


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    """Report intermediate metrics (and optionally a checkpoint) to the
    driver; outside a Train session it's a no-op print."""
    s = _get_session()
    if s is None:
        print(f"[ray_tpu.air.session] {metrics}")
        return
    s.report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    """The checkpoint to resume from (set on restore), else None."""
    s = _get_session()
    return s.last_checkpoint if s else None


def get_world_rank() -> int:
    s = _get_session()
    return s.world_rank if s else 0


def get_local_rank() -> int:
    s = _get_session()
    return s.local_rank if s else 0


def get_world_size() -> int:
    s = _get_session()
    return s.world_size if s else 1


def get_node_rank() -> int:
    s = _get_session()
    return s.node_rank if s else 0


def get_trial_name() -> str:
    s = _get_session()
    return s.trial_name if s else "default"


def get_mesh():
    """The gang's `jax.sharding.Mesh` (set by the SPMD backend), or a local
    mesh outside a session."""
    s = _get_session()
    mesh = getattr(s, "mesh", None) if s else None
    if mesh is None:
        from ..parallel.mesh import create_mesh
        mesh = create_mesh()
    return mesh


def get_dataset_shard(name: str = "train"):
    s = _get_session()
    if s is None or s.dataset_shard is None:
        return None
    if isinstance(s.dataset_shard, dict):
        return s.dataset_shard.get(name)
    return s.dataset_shard
