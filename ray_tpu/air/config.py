"""Run/scaling/failure configuration.

Capability mirror of the reference's `air/config.py` (`ScalingConfig`,
`RunConfig`, `FailureConfig`, `CheckpointConfig`).  TPU-native additions:
``topology`` (e.g. "v5e-16") and ``mesh`` (a `MeshSpec` or "dp=2,tp=4"
string) on ScalingConfig — placement becomes ICI-topology-aware bundles.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Union

from ..parallel.mesh import MeshSpec


@dataclasses.dataclass
class ScalingConfig:
    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    topology: Optional[str] = None        # e.g. "v5e-16": slice to gang on
    mesh: Union[MeshSpec, str, None] = None  # parallelism layout per worker

    @property
    def mesh_spec(self) -> Optional[MeshSpec]:
        if isinstance(self.mesh, str):
            return MeshSpec.parse(self.mesh)
        return self.mesh

    def bundle(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker or {})
        res.setdefault("CPU", 1.0)
        if self.use_tpu:
            res.setdefault("TPU", 1.0)
        return res

    def bundles(self) -> List[Dict[str, float]]:
        return [self.bundle() for _ in range(self.num_workers)]


@dataclasses.dataclass
class FailureConfig:
    max_failures: int = 0  # gang restarts from last checkpoint


@dataclasses.dataclass
class ElasticConfig:
    """Elastic gang recovery: in-memory replicated micro-checkpoints +
    fast rank replacement, so an *unannounced* TPU preemption costs
    seconds and at most ``snapshot_interval_steps`` steps instead of a
    full-gang restart from the last disk checkpoint.

    Each rank snapshots its reported train state into the object store
    every ``snapshot_interval_steps`` reports (asynchronously, off the
    step path), with the primary copy pinned on a ring-neighbor peer
    host so one host's death never loses its own shard.  On a worker or
    node death the BackendExecutor parks healthy ranks, reschedules only
    the dead ranks, restores everyone from the peer-held shards, and
    resumes at the snapshot step — falling back to the legacy
    restart-from-disk path when repair overruns ``repair_deadline_s``
    or a second failure lands mid-repair."""
    snapshot_interval_steps: int = 10   # "elastic_snapshot_interval_steps"
    repair_deadline_s: float = 30.0     # rendezvous barrier budget
    max_repairs: int = 8                # fast-repair budget per attempt
    # history depth per rank: 2 guarantees a common restore step exists
    # even when a death races a snapshot wave (ranks snapshot at the
    # same iteration boundaries, so each rank's kept steps differ by at
    # most one interval)
    keep_snapshots: int = 2


@dataclasses.dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_frequency: int = 0
    checkpoint_at_end: bool = True


@dataclasses.dataclass
class RunConfig:
    name: Optional[str] = None
    # a URI (file://, s3://, gs://) syncs experiment state + artifacts
    # there (reference: RunConfig.storage_path + SyncConfig)
    storage_path: Optional[str] = None
    failure_config: Optional[FailureConfig] = None
    checkpoint_config: Optional[CheckpointConfig] = None
    # an ElasticConfig turns on in-memory replicated micro-checkpoints
    # and fast rank replacement for unannounced worker/node deaths
    elastic_config: Optional[ElasticConfig] = None
    verbose: int = 0
    # a tune.ProgressReporter (e.g. CLIReporter); verbose>0 implies a
    # default CLIReporter when unset
    progress_reporter: Optional[Any] = None
    # dict of metric thresholds, a tune.Stopper, or a plain
    # (trial_id, result) -> bool callable (tune/stopper.py)
    stop: Optional[Union[Dict[str, Any], Callable[[str, Dict[str, Any]],
                                                  bool]]] = None
    sync_config: Optional[Any] = None   # tune.syncer.SyncConfig
