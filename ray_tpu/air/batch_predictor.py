"""BatchPredictor: checkpoint → parallel inference over a Dataset.

Capability mirror of the reference's `air.BatchPredictor`
(`python/ray/train/batch_predictor.py` — load a Predictor from a
Checkpoint on each map_batches worker, stream a Dataset through it; the
AIR side of the GPU-batch-prediction benchmark,
`doc/source/ray-air/benchmarks.rst:119`).  The predictor_fn rebuilds the
model from the checkpoint once per worker task and is applied per batch,
so inference parallelism == dataset block parallelism.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from .checkpoint import Checkpoint


class BatchPredictor:
    """``predictor_fn(checkpoint) -> (batch -> predictions)``.

    The factory runs inside each prediction task (model deserialized
    worker-side, not shipped per batch); predictions concatenate into a
    new Dataset.
    """

    def __init__(self, checkpoint: Checkpoint,
                 predictor_fn: Callable[[Checkpoint], Callable[[Any], Any]]):
        self.checkpoint = checkpoint
        self.predictor_fn = predictor_fn

    @classmethod
    def from_sklearn(cls, checkpoint: Checkpoint) -> "BatchPredictor":
        """Predictor over a SklearnTrainer checkpoint."""
        def build(ckpt: Checkpoint):
            import cloudpickle
            est = cloudpickle.loads(ckpt.to_dict()["estimator"])

            def predict(batch):
                import numpy as np
                import pandas as pd
                if isinstance(batch, pd.DataFrame):
                    return est.predict(batch.to_numpy())
                return est.predict(np.asarray(batch))
            return predict
        return cls(checkpoint, build)

    def predict(self, dataset: Any, *, batch_size: Optional[int] = None):
        """→ Dataset of predictions (one row per input row).

        A LARGE checkpoint uploads to the shared object store once and
        every block task carries only the ref (small puts live in the
        owner's in-process memory store, which remote workers cannot
        fetch — those embed in the closure, which is cheap at that
        size).  Each worker PROCESS builds the model once: the cache
        lives at module level keyed by the checkpoint blob's hash, so
        repeated blocks on one worker reuse the built predictor.
        """
        import hashlib

        import cloudpickle

        from ..util.data_carrier import store_bytes

        blob = cloudpickle.dumps(self.checkpoint.to_dict())
        # key on checkpoint AND builder: two predictors sharing one
        # checkpoint (different predictor_fn) must not reuse each other's
        # built model
        fn_tag = hashlib.sha256(
            cloudpickle.dumps(self.predictor_fn)).hexdigest()[:8]
        key = hashlib.sha256(blob).hexdigest()[:16] + "-" + fn_tag
        # shared ref-vs-inline rule (util/data_carrier): refs only when
        # the blob certainly lands in plasma, where workers CAN fetch it
        carrier = store_bytes(blob)
        ckpt_ref = carrier[1] if carrier[0] == "ref" else None
        predictor_fn = self.predictor_fn

        def _predict_batch(batch, _carrier=carrier, _key=key):
            from ray_tpu.air import batch_predictor as bp
            cached = bp._PROCESS_CACHE.get(_key)
            if cached is None:
                import cloudpickle as cp

                from ..util.data_carrier import fetch_bytes
                raw = fetch_bytes(_carrier)
                ckpt = Checkpoint.from_dict(cp.loads(raw))
                cached = (predictor_fn(ckpt), ckpt.get_preprocessor())
                bp._PROCESS_CACHE[_key] = cached
                # bounded: built models are large, workers are long-lived
                while len(bp._PROCESS_CACHE) > bp._PROCESS_CACHE_MAX:
                    bp._PROCESS_CACHE.pop(next(iter(bp._PROCESS_CACHE)))
            fn, preprocessor = cached
            if preprocessor is not None:
                batch = preprocessor.transform_batch(batch)
            return list(fn(batch))

        out = dataset.map_batches(_predict_batch, batch_size=batch_size)
        if ckpt_ref is not None:
            # the closure's ref is not arg-tracked: keep the checkpoint
            # alive at least as long as the prediction dataset
            out._batch_predictor_ckpt_ref = ckpt_ref
        return out


#: per-process predictor cache: (checkpoint, builder) hash -> batch fn;
#: insertion-ordered dict doubles as FIFO eviction at the cap
_PROCESS_CACHE: Dict[str, Callable] = {}
_PROCESS_CACHE_MAX = 2
