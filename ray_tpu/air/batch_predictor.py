"""BatchPredictor: checkpoint → parallel inference over a Dataset.

Capability mirror of the reference's `air.BatchPredictor`
(`python/ray/train/batch_predictor.py` — load a Predictor from a
Checkpoint on each map_batches worker, stream a Dataset through it; the
AIR side of the GPU-batch-prediction benchmark,
`doc/source/ray-air/benchmarks.rst:119`).  The predictor_fn rebuilds the
model from the checkpoint once per worker task and is applied per batch,
so inference parallelism == dataset block parallelism.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from .checkpoint import Checkpoint


class BatchPredictor:
    """``predictor_fn(checkpoint) -> (batch -> predictions)``.

    The factory runs inside each prediction task (model deserialized
    worker-side, not shipped per batch); predictions concatenate into a
    new Dataset.
    """

    def __init__(self, checkpoint: Checkpoint,
                 predictor_fn: Callable[[Checkpoint], Callable[[Any], Any]]):
        self.checkpoint = checkpoint
        self.predictor_fn = predictor_fn

    @classmethod
    def from_sklearn(cls, checkpoint: Checkpoint) -> "BatchPredictor":
        """Predictor over a SklearnTrainer checkpoint."""
        def build(ckpt: Checkpoint):
            import cloudpickle
            est = cloudpickle.loads(ckpt.to_dict()["estimator"])

            def predict(batch):
                import numpy as np
                import pandas as pd
                if isinstance(batch, pd.DataFrame):
                    return est.predict(batch.to_numpy())
                return est.predict(np.asarray(batch))
            return predict
        return cls(checkpoint, build)

    def predict(self, dataset: Any, *, batch_size: Optional[int] = None):
        """→ Dataset of predictions (one row per input row)."""
        ckpt_dict = self.checkpoint.to_dict()
        predictor_fn = self.predictor_fn

        def _predict_batch(batch):
            # rebuilt per task; cached per worker process via attribute
            cache_key = "_ray_tpu_batch_predictor"
            fn = getattr(_predict_batch, cache_key, None)
            if fn is None:
                fn = predictor_fn(Checkpoint.from_dict(ckpt_dict))
                setattr(_predict_batch, cache_key, fn)
            out = fn(batch)
            return list(out)

        return dataset.map_batches(_predict_batch, batch_size=batch_size)
