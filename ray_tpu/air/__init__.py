"""AIR-core equivalents: shared ML primitives.

Mirrors the capability set of the reference's `python/ray/air/`
(`Checkpoint` air/checkpoint.py:60, `ScalingConfig`/`RunConfig`/
`FailureConfig` air/config.py, `session.report` air/session.py:41,
`Result`) with TPU-first semantics: ScalingConfig speaks TPU topologies and
mesh specs, checkpoints hold jax pytrees natively.
"""

from .batch_predictor import BatchPredictor  # noqa: F401
from .checkpoint import Checkpoint  # noqa: F401
from .config import (  # noqa: F401
    CheckpointConfig,
    ElasticConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from .result import Result  # noqa: F401
from . import session  # noqa: F401
