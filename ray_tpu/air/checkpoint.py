"""Unified checkpoint: dict ⇄ directory ⇄ bytes, jax-pytree-native.

Capability mirror of the reference's `air.Checkpoint`
(/root/reference/python/ray/air/checkpoint.py:60 — dict/dir/URI
interconvertible).  TPU-first differences: sharded jax pytrees are
first-class via `from_pytree`/`to_pytree` (orbax/tensorstore layout —
per-host shard writers, restore onto ANY sharding for cross-topology
resume); dict checkpoints pickle; `ray_tpu.train.checkpointing` layers
retention/pruning on top.
"""

from __future__ import annotations

import io
import os
import pickle
import shutil
import tarfile
import tempfile
from typing import Any, Dict, Optional

_DICT_FILE = "checkpoint.pkl"
_PYTREE_DIR = "pytree"


def _pytree_saves(path: str) -> list:
    """Committed pytree save dirs under ``path``, oldest → newest
    (atomic orbax commit means presence == complete)."""
    try:
        names = os.listdir(path)
    except OSError:
        return []
    out = [n for n in names
           if n == _PYTREE_DIR
           or (n.startswith(_PYTREE_DIR + "-") and
               n[len(_PYTREE_DIR) + 1:].isdigit())]
    return sorted(out)


def _next_pytree_dir(path: str) -> str:
    saves = _pytree_saves(path)
    nums = [int(n[len(_PYTREE_DIR) + 1:]) for n in saves
            if n != _PYTREE_DIR]
    nxt = (max(nums) + 1) if nums else (1 if saves else 0)
    return f"{_PYTREE_DIR}-{nxt:06d}"


def _latest_pytree_dir(path: str):
    saves = _pytree_saves(path)
    if not saves:
        return None
    # numbered saves sort after the legacy bare name; newest wins
    return os.path.join(path, saves[-1])


class Checkpoint:
    """Immutable handle on checkpoint data, either in memory or on disk."""

    def __init__(self, *, _data: Optional[Dict[str, Any]] = None,
                 _path: Optional[str] = None):
        if (_data is None) == (_path is None):
            raise ValueError("exactly one of data dict or path required")
        self._data = _data
        self._path = _path

    # -- constructors -------------------------------------------------------
    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        return cls(_data=dict(data))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        if not os.path.isdir(path):
            raise ValueError(f"not a directory: {path}")
        return cls(_path=os.path.abspath(path))

    @classmethod
    def from_pytree(cls, tree: Any, path: Optional[str] = None
                    ) -> "Checkpoint":
        """Save a jax pytree via orbax (the tensorstore-backed sharded
        format: each host writes its own array shards, the TPU-native
        multi-host checkpoint story — SURVEY §7 P4).  ``tree`` may hold
        sharded `jax.Array`s; the layout on disk is resharding-friendly
        (see :meth:`to_pytree`)."""
        import jax
        import orbax.checkpoint as ocp
        if path is None and jax.process_count() > 1:
            raise ValueError(
                "multi-host from_pytree needs an explicit path on a "
                "SHARED filesystem (every host must save into the same "
                "directory for the coordinated shard writers to commit)")
        path = os.path.abspath(path or tempfile.mkdtemp(
            prefix="ray_tpu_ckpt_"))
        # Saves NEVER overwrite: each save commits into a fresh
        # monotonically numbered subdirectory (orbax's tmp-dir + rename
        # commit is atomic for a fresh name), so a crashed or retried
        # save can re-target the same ``path`` — the failure-retry /
        # resume pattern — without any cross-process swap dance and
        # without ever endangering the previous copy.  Gang ranks agree
        # on the index because they enumerate the same shared directory
        # after the previous save's commit barrier.  ``to_pytree`` reads
        # the NEWEST committed save.
        target = os.path.join(path, _next_pytree_dir(path))
        ckptr = ocp.StandardCheckpointer()
        try:
            # the save commits ASYNCHRONOUSLY (per-host shard writers);
            # wait_until_finished includes the cross-process commit
            # barrier (jax.distributed/SpmdConfig gangs; independent
            # single-process workers saving to one path fail loudly on
            # orbax's existing-directory check instead of corrupting it)
            ckptr.save(target, tree)
            ckptr.wait_until_finished()
        finally:
            ckptr.close()
        return cls.from_directory(path)

    def to_pytree(self, target: Any = None) -> Any:
        """Restore a pytree saved with :meth:`from_pytree`.

        With ``target`` — a matching pytree of arrays or
        `jax.ShapeDtypeStruct`s carrying `Sharding`s — arrays restore
        DIRECTLY onto those shardings, including shardings different
        from the ones they were saved under (cross-topology restore:
        save on one mesh, resume on another)."""
        import orbax.checkpoint as ocp
        if self._path is None:
            # dict checkpoints never hold a pytree dir: fail without
            # materializing the whole dict to a leaked temp directory
            raise ValueError("checkpoint holds no orbax pytree "
                             "(was it saved with from_pytree?)")
        item = _latest_pytree_dir(self._path)
        if item is None:
            raise ValueError("checkpoint holds no orbax pytree "
                             "(was it saved with from_pytree?)")
        ckptr = ocp.StandardCheckpointer()
        try:
            return ckptr.restore(item, target)
        finally:
            ckptr.close()

    @classmethod
    def from_bytes(cls, blob: bytes) -> "Checkpoint":
        obj = pickle.loads(blob)
        if isinstance(obj, dict) and obj.get("__ckpt_kind__") == "tar":
            tmp = tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
            with tarfile.open(fileobj=io.BytesIO(obj["tar"])) as tf:
                tf.extractall(tmp, filter="data")
            return cls.from_directory(tmp)
        return cls.from_dict(obj)

    # -- conversions --------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        if self._data is not None:
            return dict(self._data)
        fp = os.path.join(self._path, _DICT_FILE)
        if os.path.exists(fp):
            with open(fp, "rb") as f:
                data = pickle.load(f)
            # a preprocessor sidecar must survive the dict round trip
            # (BatchPredictor ships checkpoints as to_dict blobs)
            pf = os.path.join(self._path, self._PREPROCESSOR_FILE)
            if os.path.exists(pf) and \
                    self._PREPROCESSOR_KEY not in data:
                with open(pf, "rb") as f:
                    data[self._PREPROCESSOR_KEY] = f.read()
            return data
        # generic directory → special key holding the file map
        out: Dict[str, Any] = {}
        for root, _, files in os.walk(self._path):
            for name in files:
                full = os.path.join(root, name)
                rel = os.path.relpath(full, self._path)
                with open(full, "rb") as f:
                    out[rel] = f.read()
        return {"__files__": out}

    def to_directory(self, path: Optional[str] = None) -> str:
        path = path or tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        os.makedirs(path, exist_ok=True)
        if self._path is not None:
            if os.path.abspath(path) != self._path:
                shutil.copytree(self._path, path, dirs_exist_ok=True)
            return path
        data = self._data
        if "__files__" in data:
            for rel, blob in data["__files__"].items():
                full = os.path.join(path, rel)
                os.makedirs(os.path.dirname(full) or path, exist_ok=True)
                with open(full, "wb") as f:
                    f.write(blob)
        else:
            with open(os.path.join(path, _DICT_FILE), "wb") as f:
                pickle.dump(data, f)
        return path

    def to_bytes(self) -> bytes:
        if self._data is not None:
            return pickle.dumps(self._data)
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w") as tf:
            tf.add(self._path, arcname=".")
        return pickle.dumps({"__ckpt_kind__": "tar", "tar": buf.getvalue()})

    # -- preprocessor attachment --------------------------------------------
    _PREPROCESSOR_KEY = "_preprocessor"
    _PREPROCESSOR_FILE = "preprocessor.pkl"

    def with_preprocessor(self, preprocessor: Any) -> "Checkpoint":
        """Attach a fitted preprocessor (reference: air/checkpoint.py's
        preprocessor attachment feeding BatchPredictor/Serve —
        `python/ray/train/batch_predictor.py` applies it before every
        predict batch).

        Dict checkpoints return a NEW checkpoint; directory checkpoints
        attach IN PLACE (a ``preprocessor.pkl`` sidecar next to the
        payload — copying a multi-GB orbax tree for immutability would
        be worse than the aliasing) and return self.
        """
        import cloudpickle
        blob = cloudpickle.dumps(preprocessor)
        if self._data is not None:
            data = dict(self._data)
            data[self._PREPROCESSOR_KEY] = blob
            return Checkpoint.from_dict(data)
        # sidecar file next to the payload (kept out of the orbax
        # pytree dirs, which must stay orbax-owned)
        with open(os.path.join(self._path, self._PREPROCESSOR_FILE),
                  "wb") as f:
            f.write(blob)
        return self

    def get_preprocessor(self) -> Optional[Any]:
        import cloudpickle
        if self._data is not None:
            blob = self._data.get(self._PREPROCESSOR_KEY)
            if blob is None:
                # a directory checkpoint shipped via to_dict carries the
                # sidecar in its file map
                blob = self._data.get("__files__", {}).get(
                    self._PREPROCESSOR_FILE)
            return cloudpickle.loads(blob) if blob is not None else None
        fp = os.path.join(self._path, self._PREPROCESSOR_FILE)
        if os.path.exists(fp):
            with open(fp, "rb") as f:
                return cloudpickle.loads(f.read())
        # a dict checkpoint persisted to a directory (CheckpointManager)
        # carries the key inside checkpoint.pkl, not as a sidecar
        dp = os.path.join(self._path, _DICT_FILE)
        if os.path.exists(dp):
            with open(dp, "rb") as f:
                blob = pickle.load(f).get(self._PREPROCESSOR_KEY)
            if blob is not None:
                return cloudpickle.loads(blob)
        return None

    @property
    def path(self) -> Optional[str]:
        return self._path

    def __repr__(self):
        src = self._path if self._path else f"dict[{len(self._data)} keys]"
        return f"Checkpoint({src})"
