"""Result of a training/tuning run (reference: `python/ray/air/result.py`)."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from .checkpoint import Checkpoint


@dataclasses.dataclass
class Result:
    metrics: Dict[str, Any]
    checkpoint: Optional[Checkpoint] = None
    error: Optional[BaseException] = None
    path: Optional[str] = None
    metrics_history: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list)

    @property
    def config(self) -> Optional[Dict[str, Any]]:
        return self.metrics.get("config") if self.metrics else None
