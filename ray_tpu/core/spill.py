"""Object spilling to external storage.

Capability mirror of the reference's spill pipeline (plasma → dedicated
spill workers → `ExternalStorage` filesystem backend,
`python/ray/_private/external_storage.py:72,246`; orchestrated by
`src/ray/raylet/local_object_manager.cc`).  Simplified topology: the
process that hits `StoreFullError` writes the serialized object to the
session spill directory itself and registers the location in the
controller KV, so any node can restore it (shared-fs or single-machine
sessions; a remote-read RPC slots in for multi-host without changing
callers).
"""

from __future__ import annotations

import os
import tempfile
from typing import List, Optional

_NS = "spill"


def spill_root() -> str:
    base = os.environ.get("RAY_TPU_SESSION_DIR") or tempfile.gettempdir()
    path = os.path.join(base, "spill")
    os.makedirs(path, exist_ok=True)
    return path


def write_object(oid: bytes, parts: List[memoryview]) -> str:
    """Write serialized parts to a spill file; returns the path."""
    path = os.path.join(spill_root(), oid.hex())
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        for p in parts:
            f.write(bytes(p))
    os.replace(tmp, path)
    return path


def kv_entry(oid: bytes) -> dict:
    return {"ns": _NS, "key": oid}


def read_file(path: str) -> Optional[bytes]:
    try:
        with open(path, "rb") as f:
            return f.read()
    except FileNotFoundError:
        return None


def delete_file(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass
