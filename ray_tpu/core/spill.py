"""Object spilling to external storage.

Capability mirror of the reference's spill pipeline (plasma → dedicated
spill workers → `ExternalStorage` backends,
`python/ray/_private/external_storage.py:72,246,368`; orchestrated by
`src/ray/raylet/local_object_manager.cc`).  Two triggers feed it:

1. **Writer-inline** — a put that hits `StoreFullError` spills its own
   serialized stream (driver.py put path), so creates never fail while
   external storage has room.
2. **Nodelet-orchestrated** — the nodelet's spill loop watches store
   usage and proactively spills pinned primary copies above the
   high-water mark (nodelet.py `_spill_loop`), the role the reference's
   raylet `LocalObjectManager::SpillObjects` plays.

Either way the restore URL is registered in the controller KV
(namespace ``spill``), so any process whose storage backend is shared
(session dir on one machine, bucket URI across hosts) can restore.
The backend is pluggable via the ``spill_storage_uri`` flag — see
`external_storage.py`.

Integrity: every spill file carries a CRC32 trailer
(external_storage.py) verified on restore — a corrupt or truncated
file is treated exactly like a MISSING copy (``read_file`` returns
None) so the fetch ladder falls through to alternates/lineage and
garbage is never deserialized.  Filesystem chaos sites ``spill.write``
/ ``spill.restore`` / ``spill.delete`` inject ENOSPC/EIO here; the
degradation ladder is: proactive spill skips the object (it stays in
memory), capacity-pressure spill retains in memory + backpressures the
put, restore failure falls through the existing fetch ladder, delete
failure only leaks a file.
"""

from __future__ import annotations

from typing import List, Optional

from . import external_storage


def _fi():
    # lazy: this module loads inside the ray_tpu.api import chain, and
    # importing ..util there would close a circular import (util's
    # __init__ re-enters api via placement_group)
    from ..util import fault_injection
    return fault_injection

_NS = "spill"

SPILL_WRITE_SITE = "spill.write"
SPILL_RESTORE_SITE = "spill.restore"
SPILL_DELETE_SITE = "spill.delete"


def _node_tag() -> str:
    import os
    return os.environ.get("RAY_TPU_NODE_ID", "driver")[:12]


def count_fault(site: str, outcome: str) -> None:
    """Fold one storage-fault degradation into the metrics battery."""
    from . import runtime_metrics as rtm
    rtm.STORAGE_FAULTS.inc(tags={"site": site, "outcome": outcome})


def spill_root() -> str:
    return external_storage.default_spill_root()


def write_object(oid: bytes, parts: List[memoryview]) -> str:
    """Spill serialized parts to the configured backend; returns the URL.
    Raises ``OSError`` (ENOSPC/EIO/...) when the backend cannot absorb
    the object — callers own the degradation (retain in memory)."""
    _fi().fs_point(SPILL_WRITE_SITE, oid.hex())
    return external_storage.get_storage().spill(oid, parts)


def kv_entry(oid: bytes) -> dict:
    return {"ns": _NS, "key": oid}


def read_file(url: str) -> Optional[bytes]:
    """Restore one spilled object, or None when the copy is unusable
    (absent, unreadable, CRC mismatch) — None means "missing" to every
    caller, which falls through the fetch ladder to lineage."""
    try:
        _fi().fs_point(SPILL_RESTORE_SITE, url)
        raw = external_storage.get_storage().restore(url)
    except OSError:
        count_fault(SPILL_RESTORE_SITE, "missing")
        return None
    if raw is None:
        return None
    data, state = external_storage.check_crc(raw)
    if state == "corrupt":
        # truncated/bit-flipped spill file: NEVER deserialized — drop
        # the copy and let the ladder reconstruct
        count_fault(SPILL_RESTORE_SITE, "corrupt_dropped")
        return None
    from . import runtime_metrics as rtm
    rtm.OBJECTS_RESTORED.inc(tags={"node": _node_tag()})
    return data


def delete_file(url: str) -> None:
    try:
        _fi().fs_point(SPILL_DELETE_SITE, url)
        external_storage.get_storage().delete(url)
    except OSError:
        count_fault(SPILL_DELETE_SITE, "leaked")
