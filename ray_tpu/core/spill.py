"""Object spilling to external storage.

Capability mirror of the reference's spill pipeline (plasma → dedicated
spill workers → `ExternalStorage` backends,
`python/ray/_private/external_storage.py:72,246,368`; orchestrated by
`src/ray/raylet/local_object_manager.cc`).  Two triggers feed it:

1. **Writer-inline** — a put that hits `StoreFullError` spills its own
   serialized stream (driver.py put path), so creates never fail while
   external storage has room.
2. **Nodelet-orchestrated** — the nodelet's spill loop watches store
   usage and proactively spills pinned primary copies above the
   high-water mark (nodelet.py `_spill_loop`), the role the reference's
   raylet `LocalObjectManager::SpillObjects` plays.

Either way the restore URL is registered in the controller KV
(namespace ``spill``), so any process whose storage backend is shared
(session dir on one machine, bucket URI across hosts) can restore.
The backend is pluggable via the ``spill_storage_uri`` flag — see
`external_storage.py`.
"""

from __future__ import annotations

from typing import List, Optional

from . import external_storage

_NS = "spill"


def spill_root() -> str:
    return external_storage.default_spill_root()


def write_object(oid: bytes, parts: List[memoryview]) -> str:
    """Spill serialized parts to the configured backend; returns the URL."""
    return external_storage.get_storage().spill(oid, parts)


def kv_entry(oid: bytes) -> dict:
    return {"ns": _NS, "key": oid}


def read_file(url: str) -> Optional[bytes]:
    data = external_storage.get_storage().restore(url)
    if data is not None:
        import os

        from . import runtime_metrics as rtm
        rtm.OBJECTS_RESTORED.inc(tags={
            "node": os.environ.get("RAY_TPU_NODE_ID", "driver")[:12]})
    return data


def delete_file(url: str) -> None:
    external_storage.get_storage().delete(url)
