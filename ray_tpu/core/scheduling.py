"""Cluster scheduling policies.

Implements the reference's two-level scheduling policies over a cluster
resource view (/root/reference/src/ray/raylet/scheduling/policy/
hybrid_scheduling_policy.h:23-46 for the hybrid score; bundle_scheduling_policy.cc
for placement-group bundle packing).  The view is a dict
``{node_id_hex: NodeView}`` maintained from heartbeats; every nodelet and the
controller run the same code, so spillback decisions agree cluster-wide.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Tuple

from .task_spec import EPS, ResourceSet


class NodeView:
    __slots__ = ("node_id", "addr", "available", "total", "alive", "labels",
                 "version", "draining", "suspect", "unreachable", "disk")

    def __init__(self, node_id: str, addr: str, available: Dict[str, float],
                 total: Dict[str, float], alive: bool = True,
                 labels: Optional[Dict[str, str]] = None,
                 version: int = 0, draining: bool = False,
                 suspect: bool = False, unreachable=None,
                 disk: str = "ok"):
        self.node_id = node_id
        self.addr = addr
        self.available = ResourceSet(available)
        self.total = ResourceSet(total)
        self.alive = alive
        self.labels = labels or {}
        # Lamport stamp of the last change to THIS node's view; the
        # versioned syncer ships only views newer than the receiver's
        # high-water mark (reference: RaySyncer per-node versioned views,
        # src/ray/common/ray_syncer/ray_syncer.h:75-88).
        self.version = version
        # DRAINING: the node is evacuating ahead of a planned departure
        # (maintenance / preemption notice).  Still alive — in-flight work
        # finishes, objects stay fetchable — but never a target for new
        # leases, actor placements, or PG bundles.
        self.draining = draining
        # SUSPECT: the controller's link to the node is down but probing
        # peers still reach it (gray failure / controller-only
        # partition).  Quarantined — no new leases, placements, or
        # serve routes — but its actors and objects are untouched; it
        # rejoins intact when the link heals inside the grace budget.
        self.suspect = suspect
        # Peers this node freshly reported it cannot reach (directed:
        # this-node -> peer).  Scheduling avoids placing a task here
        # when its args live only on an unreachable peer.
        self.unreachable: set = set(unreachable or ())
        # Disk-health watermark state of the node's spill filesystem
        # ("ok" | "low" | "red", nodelet disk monitor via heartbeats).
        # RED nodes are soft-excluded as lease spill-back targets —
        # work spilled there could neither spill objects nor absorb a
        # capacity-pressure put.  LOW is operator-facing only.
        self.disk = disk or "ok"

    def to_wire(self):
        return {"id": self.node_id, "addr": self.addr,
                "avail": self.available.to_dict(), "total": self.total.to_dict(),
                "alive": self.alive, "labels": self.labels,
                "ver": self.version, "draining": self.draining,
                "sus": self.suspect, "unreach": sorted(self.unreachable),
                "disk": self.disk}

    @classmethod
    def from_wire(cls, d):
        return cls(d["id"], d["addr"], d["avail"], d["total"], d["alive"],
                   d.get("labels"), d.get("ver", 0), d.get("draining", False),
                   d.get("sus", False), d.get("unreach"),
                   d.get("disk", "ok"))


def is_feasible(view: NodeView, request: ResourceSet) -> bool:
    return view.alive and not view.draining and not view.suspect \
        and view.total.fits(request)


def _links_ok(view: NodeView, arg_nodes) -> bool:
    """True when ``view`` can fetch from every node in ``arg_nodes``
    (per its own fresh reachability reports)."""
    return not any(b != view.node_id and b in view.unreachable
                   for b in arg_nodes)


def hybrid_policy(
    views: Dict[str, NodeView],
    request: ResourceSet,
    local_node_id: Optional[str] = None,
    spread_threshold: float = 0.5,
    strategy: Optional[dict] = None,
    rng: Optional[random.Random] = None,
    arg_nodes: Optional[set] = None,
) -> Optional[str]:
    """Pick a node id for ``request``, or None if infeasible everywhere.

    Hybrid semantics from the reference: prefer nodes that can run the task
    *now* over merely-feasible ones; among available nodes score by critical
    resource utilization, truncated below ``spread_threshold`` so an
    under-utilized cluster packs (ties broken toward the local node, then
    lexical node id for determinism), and spreads once utilization passes the
    threshold.

    ``arg_nodes``: nodes the task's arguments live on.  Candidates that
    freshly reported one of them unreachable (connectivity matrix via
    the view sync) are avoided — placing there would wedge the task's
    arg fetch behind a severed link.  The filter is SOFT: if it would
    empty the candidate set (stale gossip, full partition) placement
    proceeds unfiltered and the fetch ladder's relay path is the
    safety net.  Hard node affinity is never filtered.
    """
    strategy = strategy or {}
    if arg_nodes and not strategy.get("node_id"):
        ok_views = {nid: v for nid, v in views.items()
                    if _links_ok(v, arg_nodes)}
        if ok_views:
            views = ok_views
    if strategy.get("node_id"):
        nv = views.get(strategy["node_id"])
        if nv is not None and is_feasible(nv, request):
            if strategy.get("soft") or nv.available.fits(request):
                return nv.node_id
            return nv.node_id  # hard affinity: queue there
        if not strategy.get("soft"):
            return None
        # soft affinity to a dead/draining/infeasible node falls back to
        # normal placement (matches the reference's soft NodeAffinity) —
        # returning None here would pin the task to a corpse forever
        strategy = {k: v for k, v in strategy.items()
                    if k not in ("node_id", "soft")}
        return hybrid_policy(views, request, local_node_id,
                             spread_threshold, strategy, rng, arg_nodes)
    if strategy.get("spread"):
        # Round-robin over feasible nodes, preferring available ones.
        avail = [n for n in views.values()
                 if is_feasible(n, request) and n.available.fits(request)]
        feas = [n for n in views.values() if is_feasible(n, request)]
        pool = avail or feas
        if not pool:
            return None
        r = rng or random
        return r.choice(pool).node_id

    best: List[Tuple[float, int, str]] = []
    for n in views.values():
        if not is_feasible(n, request):
            continue
        available_now = n.available.fits(request)
        util = (n.total.res and _util_after(n, request)) or 0.0
        score = 0.0 if util < spread_threshold else util
        # Sort key: available first, then low score, then local, then id.
        local_bias = 0 if n.node_id == local_node_id else 1
        best.append((score + (0 if available_now else 10.0), local_bias, n.node_id))
    if not best:
        return None
    best.sort()
    return best[0][2]


def _util_after(n: NodeView, request: ResourceSet) -> float:
    remaining = n.available.copy()
    remaining.acquire(request)
    return remaining.utilization(n.total)


def pack_bundles(
    views: Dict[str, NodeView],
    bundles: List[Dict[str, float]],
    strategy: str,
) -> Optional[List[str]]:
    """Assign each bundle a node id honoring a placement-group strategy.

    PACK: minimize node count (best effort) — sort nodes by free capacity and
    fill.  STRICT_PACK: all on one node.  SPREAD: best-effort distinct nodes.
    STRICT_SPREAD: must be distinct nodes.  Returns None if unplaceable now.
    (reference: src/ray/raylet/scheduling/policy/bundle_scheduling_policy.cc)

    Bundles must land on MUTUALLY REACHABLE nodes: a gang spanning an
    asymmetric partition (A↛B per the connectivity matrix) could place
    but never rendezvous, so a candidate that cannot reach — or is not
    reached by — an already-chosen node is skipped (unplaceable now; the
    matrix entries expire when the link heals).
    """
    reqs = [ResourceSet(b) for b in bundles]
    nodes = [n for n in views.values()
             if n.alive and not n.draining and not n.suspect]
    scratch = {n.node_id: n.available.copy() for n in nodes}
    by_id = {n.node_id: n for n in nodes}

    def fits(nid, req):
        return scratch[nid].fits(req)

    def take(nid, req):
        scratch[nid].acquire(req)

    def reachable_with(nid, placed) -> bool:
        n = by_id[nid]
        for pid in placed:
            if pid is None or pid == nid:
                continue
            p = by_id[pid]
            if pid in n.unreachable or nid in p.unreachable:
                return False
        return True

    if strategy == "STRICT_PACK":
        for n in nodes:
            if all(_seq_fits(scratch[n.node_id].copy(), reqs)):
                return [n.node_id] * len(reqs)
        return None

    order = sorted(nodes, key=lambda n: -sum(n.available.res.values()))
    placement: List[Optional[str]] = [None] * len(reqs)
    if strategy in ("PACK", ""):
        for i, req in enumerate(reqs):
            placed = False
            for n in order:
                if fits(n.node_id, req) \
                        and reachable_with(n.node_id, placement):
                    take(n.node_id, req)
                    placement[i] = n.node_id
                    placed = True
                    break
            if not placed:
                return None
        return placement  # type: ignore[return-value]

    # SPREAD / STRICT_SPREAD
    used_nodes: set = set()
    for i, req in enumerate(reqs):
        candidates = sorted(order, key=lambda n: (n.node_id in used_nodes,
                                                  -sum(scratch[n.node_id].res.values())))
        placed = False
        for n in candidates:
            if strategy == "STRICT_SPREAD" and n.node_id in used_nodes:
                continue
            if not reachable_with(n.node_id, placement):
                continue
            if fits(n.node_id, req):
                take(n.node_id, req)
                used_nodes.add(n.node_id)
                placement[i] = n.node_id
                placed = True
                break
        if not placed:
            return None
    return placement  # type: ignore[return-value]


def _seq_fits(avail: ResourceSet, reqs: List[ResourceSet]):
    for r in reqs:
        yield avail.fits(r)
        avail.acquire(r)
