"""Controller state persistence: snapshot + write-ahead log.

Capability mirror of the reference's GCS storage backends
(/root/reference/src/ray/gcs/store_client/in_memory_store_client.h:27 →
RedisGcsTableStorage, gcs_table_storage.h:357-361): the control plane's
metadata tables survive a controller crash, so a restarted controller
resumes with its actors, placement groups, KV, and jobs intact while live
nodelets re-register over their heartbeat loops.

Design: no external store (the reference needs Redis; a TPU-pod control
plane should not).  Tables are msgpack'd to a snapshot file; every mutation
between snapshots appends one length-prefixed, CRC-guarded msgpack record
to a WAL.  Recovery = load snapshot, replay WAL.  The WAL is compacted into
a fresh snapshot every ``compact_every`` appends.  Mutation rate on the
controller is low (actors/PGs/KV, never tasks), so fsync-per-append is
affordable.

WAL format v2: the file opens with an 8-byte magic, then records of
``<u32 len><u32 crc32><payload>``.  A record whose CRC does not match is
treated exactly like a torn tail — replay stops at the last valid prefix
(a corrupt middle record must not unpack garbage into the tables).
CRC-less v1 files (no magic, ``<u32 len><payload>`` records) stay
readable; an existing v1 WAL keeps its format until the next compaction.

Replication: the store carries a monotonic ``seq`` and an optional
``tap`` callback fired after every locally durable append — the leader's
HA replicator (core/ha.py) streams those records to a hot-standby
controller on a peer host, which appends them to its OWN store via
:meth:`append_replica` (the lease + epoch are thereby "persisted in both
WALs").
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Any, Callable, Dict, List, Optional

import msgpack

from ..exceptions import WalWriteError
from ..util import fault_injection as fi

_LEN = struct.Struct("<I")
_CRC = struct.Struct("<I")
WAL_MAGIC = b"RTPUWAL2"

# Chaos sites for the filesystem fault domain (util/fault_injection.py).
# Keyed "<dirname>:<op>" so a plan can target the leader's store without
# also poisoning an in-process standby replaying the same record ops.
WAL_APPEND_SITE = "wal.append"
WAL_FSYNC_SITE = "wal.fsync"
WAL_SNAPSHOT_SITE = "wal.snapshot"


def _pack(obj: Any) -> bytes:
    return msgpack.packb(obj, use_bin_type=True)


def _unpack(raw: bytes) -> Any:
    return msgpack.unpackb(raw, raw=False, strict_map_key=False)


def fsync_dir(path: str) -> None:
    """fsync a DIRECTORY so a rename/unlink inside it is itself durable.
    ``os.replace`` orders the data blocks, not the directory entry — on
    power loss the rename can vanish, resurrecting a stale snapshot
    against a WAL that was already deleted.

    Raises the ``OSError``: swallowing it here silently demoted every
    caller's durability story (a failed directory fsync means the rename
    ordering is NOT guaranteed) — callers decide whether that is fatal
    (WAL poison) or a degradation (compaction keeps the WAL)."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class ControllerStore:
    """Durable home of the controller's metadata tables."""

    def __init__(self, persist_dir: str, compact_every: int = 512,
                 fsync: bool = True):
        self.dir = persist_dir
        os.makedirs(persist_dir, exist_ok=True)
        self.snap_path = os.path.join(persist_dir, "controller.snapshot")
        self.wal_path = os.path.join(persist_dir, "controller.wal")
        self._wal = None
        self._wal_v2 = True      # decided when the file is opened
        self._appends = 0
        self._compact_every = compact_every
        self._fsync = fsync
        self._snapshot_provider = None  # set by the controller
        #: records appended (locally durable) since this store object was
        #: created — the replication stream's sequence domain
        self.seq = 0
        #: called with the record list after each durable local append
        #: (core/ha.py wires the leader's replicator here)
        self.tap: Optional[Callable[[List[Any]], None]] = None
        #: WAL hot-path timing (flight-recorder / attribution source):
        #: every append's wall time plus the fsync share, so "the
        #: controller stalls on fsync" is measurable, not folklore
        self.timing: Dict[str, float] = {
            "appends": 0, "append_s": 0.0, "append_max_s": 0.0,
            "fsync_s": 0.0, "fsync_max_s": 0.0,
            "append_errors": 0, "fsync_errors": 0, "snapshot_errors": 0}
        #: set to the failure detail by the FIRST write/fsync OSError:
        #: after one failed fsync the page-cache state of the log is
        #: unknowable (fsyncgate), so every later append raises
        #: WalWriteError — the HA self-fence path is the only exit
        self.poisoned: Optional[str] = None

    # -- recovery ------------------------------------------------------------
    def load(self) -> Optional[Dict[str, Any]]:
        """Snapshot + WAL replay → tables dict, or None on first boot."""
        state: Optional[Dict[str, Any]] = None
        if os.path.exists(self.snap_path):
            with open(self.snap_path, "rb") as f:
                state = _unpack(f.read())
        records = self._read_wal()
        if records and state is None:
            state = _empty_tables()
        for rec in records:
            _apply(state, rec)
        return state

    def _read_wal(self) -> List[tuple]:
        if not os.path.exists(self.wal_path):
            return []
        out = []
        with open(self.wal_path, "rb") as f:
            raw = f.read()
        off = 0
        v2 = raw.startswith(WAL_MAGIC)
        if v2:
            off = len(WAL_MAGIC)
        head = _LEN.size + (_CRC.size if v2 else 0)
        while off + head <= len(raw):
            (n,) = _LEN.unpack_from(raw, off)
            off += _LEN.size
            if v2:
                (crc,) = _CRC.unpack_from(raw, off)
                off += _CRC.size
            if off + n > len(raw):
                break  # torn tail write: discard (snapshot+prefix is valid)
            blob = raw[off:off + n]
            if v2 and zlib.crc32(blob) & 0xFFFFFFFF != crc:
                # corrupt record: everything at and after it is suspect —
                # stop at the last valid prefix, same as a torn tail
                break
            try:
                out.append(_unpack(blob))
            except Exception:
                break  # v1 record that doesn't unpack: treat as torn
            off += n
        return out

    # -- mutation log --------------------------------------------------------
    def _open_wal(self):
        exists = os.path.exists(self.wal_path) \
            and os.path.getsize(self.wal_path) > 0
        self._wal = open(self.wal_path, "ab")
        if not exists:
            self._wal.write(WAL_MAGIC)
            self._wal_v2 = True
        else:
            # keep appending in whatever format the file started with —
            # mixing CRC and CRC-less records in one file is unreadable
            with open(self.wal_path, "rb") as f:
                self._wal_v2 = f.read(len(WAL_MAGIC)) == WAL_MAGIC

    def append(self, *record: Any) -> int:
        """Durably append one mutation record; returns its seq.  Feeds
        the replication tap after the local fsync (a record is offered to
        the standby only once it can no longer be lost locally)."""
        seq = self._append_local(list(record))
        if self.tap is not None:
            self.tap(list(record))
        return seq

    def append_replica(self, record: List[Any]) -> int:
        """Append a record RECEIVED over replication (standby side): same
        durability, but never re-fed to the tap (no echo loops)."""
        return self._append_local(list(record))

    def _poison(self, op: str, exc: OSError) -> None:
        """First write/fsync failure: poison the store and surface the
        typed error.  The failed record was never fed to the replication
        tap (append() raises before tap), so nothing unacked ships."""
        self.timing[f"{op}_errors"] += 1
        self.poisoned = f"{op} failed: {exc}"
        raise WalWriteError(op, str(exc)) from exc

    def _append_local(self, record: List[Any]) -> int:
        import time as _time
        if self.poisoned is not None:
            raise WalWriteError("append", self.poisoned)
        t0 = _time.perf_counter()
        if self._wal is None:
            self._open_wal()
        blob = _pack(record)
        if self._wal_v2:
            frame = _LEN.pack(len(blob)) \
                + _CRC.pack(zlib.crc32(blob) & 0xFFFFFFFF) + blob
        else:
            frame = _LEN.pack(len(blob)) + blob
        key = f"{os.path.basename(self.dir)}:" \
              f"{record[0] if record else ''}"
        try:
            fi.fs_point(WAL_APPEND_SITE, key)
            self._wal.write(frame)
            self._wal.flush()
        except OSError as e:
            self._poison("append", e)
        if self._fsync:
            tf = _time.perf_counter()
            try:
                fi.fs_point(WAL_FSYNC_SITE, key)
                os.fsync(self._wal.fileno())
            except OSError as e:
                self._poison("fsync", e)
            dt_f = _time.perf_counter() - tf
            self.timing["fsync_s"] += dt_f
            if dt_f > self.timing["fsync_max_s"]:
                self.timing["fsync_max_s"] = dt_f
        dt = _time.perf_counter() - t0
        self.timing["appends"] += 1
        self.timing["append_s"] += dt
        if dt > self.timing["append_max_s"]:
            self.timing["append_max_s"] = dt
        self.seq += 1
        self._appends += 1
        if self._appends >= self._compact_every \
                and self._snapshot_provider is not None:
            self.snapshot(self._snapshot_provider())
        return self.seq

    def snapshot(self, tables: Dict[str, Any]) -> bool:
        """Compact the WAL into a fresh snapshot.  Compaction is an
        OPTIMIZATION: on any fs failure the dance rolls back, the WAL is
        KEPT (replaying it over an older — or even the just-renamed —
        snapshot is idempotent) and appends continue unpoisoned; returns
        False so callers can tell the compaction did not land."""
        tmp = self.snap_path + ".tmp"
        try:
            fi.fs_point(WAL_SNAPSHOT_SITE,
                        f"{os.path.basename(self.dir)}:snapshot")
            with open(tmp, "wb") as f:
                f.write(_pack(tables))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.snap_path)
            if self._fsync:
                # make the rename itself durable before the WAL goes away
                fsync_dir(self.dir)
        except OSError:
            self.timing["snapshot_errors"] += 1
            try:
                os.unlink(tmp)
            except OSError:
                pass
            self._appends = 0  # retry at the next compaction threshold
            return False
        if self._wal is not None:
            self._wal.close()
            self._wal = None
        try:
            os.unlink(self.wal_path)
            if self._fsync:
                fsync_dir(self.dir)
        except OSError:
            # unlink durability unknown: a resurrected WAL replays over
            # the new snapshot, which is idempotent — degrade, count
            self.timing["snapshot_errors"] += 1
        self._appends = 0
        return True

    def close(self) -> None:
        if self._wal is not None:
            self._wal.close()
            self._wal = None


def _empty_tables() -> Dict[str, Any]:
    return {"kv": {}, "actors": {}, "pgs": {}, "jobs": {},
            "named_actors": {}, "draining_nodes": [], "suspect_nodes": [],
            "quarantine": {}, "ha_epoch": 0}


def _apply(state: Dict[str, Any], rec: List[Any]) -> None:
    """Replay one WAL record onto the tables."""
    op = rec[0]
    if op == "kv_put":
        _, ns, key, value = rec
        state["kv"].setdefault(ns, {})[key] = value
    elif op == "kv_del":
        _, ns, key = rec
        state["kv"].get(ns, {}).pop(key, None)
    elif op == "actor":
        state["actors"][rec[1]["actor_id"]] = rec[1]
        name = rec[1].get("name")
        if name:
            state["named_actors"][name] = rec[1]["actor_id"]
    elif op == "actor_del":
        doomed = state["actors"].pop(rec[1], None)
        if doomed and doomed.get("name"):
            state["named_actors"].pop(doomed["name"], None)
    elif op == "pg":
        state["pgs"][rec[1]["pg_id"]] = rec[1]
    elif op == "pg_del":
        state["pgs"].pop(rec[1], None)
    elif op == "job":
        state["jobs"][rec[1]] = rec[2]
    elif op == "job_del":
        state["jobs"].pop(rec[1], None)
    elif op == "drain":
        # a node entered DRAINING: a restarted controller must keep it
        # out of the placement pool and resume/finish the drain
        nodes = state.setdefault("draining_nodes", [])
        if rec[1] not in nodes:
            nodes.append(rec[1])
    elif op == "drain_del":
        nodes = state.setdefault("draining_nodes", [])
        if rec[1] in nodes:
            nodes.remove(rec[1])
    elif op == "suspect":
        # a node entered SUSPECT quarantine (controller link down, peers
        # still reach it): a restarted/promoted controller must inherit
        # the quarantine — actors/objects stay untouched while the grace
        # budget (restarted fresh on restore) runs down
        nodes = state.setdefault("suspect_nodes", [])
        if rec[1] not in nodes:
            nodes.append(rec[1])
    elif op == "suspect_del":
        nodes = state.setdefault("suspect_nodes", [])
        if rec[1] in nodes:
            nodes.remove(rec[1])
    elif op == "quarantine":
        # a poison quarantine was imposed (task signature or crash-
        # looped actor): a restarted/promoted controller must keep
        # failing the signature fast — the record carries its own wall
        # timestamps (since/until/evidence), stamped by the HANDLER, so
        # replay stays clock-free and deterministic
        state.setdefault("quarantine", {})[rec[1]["sig"]] = rec[1]
    elif op == "quarantine_del":
        state.setdefault("quarantine", {}).pop(rec[1], None)
    elif op == "epoch":
        # leader-lease epoch: monotonic across failovers; a controller
        # must never serve at an epoch below one it has durably seen
        state["ha_epoch"] = max(int(state.get("ha_epoch", 0) or 0),
                                int(rec[1]))
