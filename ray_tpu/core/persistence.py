"""Controller state persistence: snapshot + write-ahead log.

Capability mirror of the reference's GCS storage backends
(/root/reference/src/ray/gcs/store_client/in_memory_store_client.h:27 →
RedisGcsTableStorage, gcs_table_storage.h:357-361): the control plane's
metadata tables survive a controller crash, so a restarted controller
resumes with its actors, placement groups, KV, and jobs intact while live
nodelets re-register over their heartbeat loops.

Design: no external store (the reference needs Redis; a TPU-pod control
plane should not).  Tables are msgpack'd to a snapshot file; every mutation
between snapshots appends one length-prefixed msgpack record to a WAL.
Recovery = load snapshot, replay WAL.  The WAL is compacted into a fresh
snapshot every ``compact_every`` appends.  Mutation rate on the controller
is low (actors/PGs/KV, never tasks), so fsync-per-append is affordable.
"""

from __future__ import annotations

import os
import struct
from typing import Any, Dict, List, Optional

import msgpack

_LEN = struct.Struct("<I")


def _pack(obj: Any) -> bytes:
    return msgpack.packb(obj, use_bin_type=True)


def _unpack(raw: bytes) -> Any:
    return msgpack.unpackb(raw, raw=False, strict_map_key=False)


class ControllerStore:
    """Durable home of the controller's metadata tables."""

    def __init__(self, persist_dir: str, compact_every: int = 512,
                 fsync: bool = True):
        self.dir = persist_dir
        os.makedirs(persist_dir, exist_ok=True)
        self.snap_path = os.path.join(persist_dir, "controller.snapshot")
        self.wal_path = os.path.join(persist_dir, "controller.wal")
        self._wal = None
        self._appends = 0
        self._compact_every = compact_every
        self._fsync = fsync
        self._snapshot_provider = None  # set by the controller

    # -- recovery ------------------------------------------------------------
    def load(self) -> Optional[Dict[str, Any]]:
        """Snapshot + WAL replay → tables dict, or None on first boot."""
        state: Optional[Dict[str, Any]] = None
        if os.path.exists(self.snap_path):
            with open(self.snap_path, "rb") as f:
                state = _unpack(f.read())
        records = self._read_wal()
        if records and state is None:
            state = _empty_tables()
        for rec in records:
            _apply(state, rec)
        return state

    def _read_wal(self) -> List[tuple]:
        if not os.path.exists(self.wal_path):
            return []
        out = []
        with open(self.wal_path, "rb") as f:
            raw = f.read()
        off = 0
        while off + _LEN.size <= len(raw):
            (n,) = _LEN.unpack_from(raw, off)
            off += _LEN.size
            if off + n > len(raw):
                break  # torn tail write: discard (snapshot+prefix is valid)
            out.append(_unpack(raw[off:off + n]))
            off += n
        return out

    # -- mutation log --------------------------------------------------------
    def append(self, *record: Any) -> None:
        if self._wal is None:
            self._wal = open(self.wal_path, "ab")
        blob = _pack(list(record))
        self._wal.write(_LEN.pack(len(blob)) + blob)
        self._wal.flush()
        if self._fsync:
            os.fsync(self._wal.fileno())
        self._appends += 1
        if self._appends >= self._compact_every \
                and self._snapshot_provider is not None:
            self.snapshot(self._snapshot_provider())

    def snapshot(self, tables: Dict[str, Any]) -> None:
        tmp = self.snap_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(_pack(tables))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.snap_path)
        if self._wal is not None:
            self._wal.close()
            self._wal = None
        try:
            os.unlink(self.wal_path)
        except OSError:
            pass
        self._appends = 0

    def close(self) -> None:
        if self._wal is not None:
            self._wal.close()
            self._wal = None


def _empty_tables() -> Dict[str, Any]:
    return {"kv": {}, "actors": {}, "pgs": {}, "jobs": {},
            "named_actors": {}, "draining_nodes": []}


def _apply(state: Dict[str, Any], rec: List[Any]) -> None:
    """Replay one WAL record onto the tables."""
    op = rec[0]
    if op == "kv_put":
        _, ns, key, value = rec
        state["kv"].setdefault(ns, {})[key] = value
    elif op == "kv_del":
        _, ns, key = rec
        state["kv"].get(ns, {}).pop(key, None)
    elif op == "actor":
        state["actors"][rec[1]["actor_id"]] = rec[1]
        name = rec[1].get("name")
        if name:
            state["named_actors"][name] = rec[1]["actor_id"]
    elif op == "actor_del":
        doomed = state["actors"].pop(rec[1], None)
        if doomed and doomed.get("name"):
            state["named_actors"].pop(doomed["name"], None)
    elif op == "pg":
        state["pgs"][rec[1]["pg_id"]] = rec[1]
    elif op == "pg_del":
        state["pgs"].pop(rec[1], None)
    elif op == "job":
        state["jobs"][rec[1]] = rec[2]
    elif op == "job_del":
        state["jobs"].pop(rec[1], None)
    elif op == "drain":
        # a node entered DRAINING: a restarted controller must keep it
        # out of the placement pool and resume/finish the drain
        nodes = state.setdefault("draining_nodes", [])
        if rec[1] not in nodes:
            nodes.append(rec[1])
    elif op == "drain_del":
        nodes = state.setdefault("draining_nodes", [])
        if rec[1] in nodes:
            nodes.remove(rec[1])
